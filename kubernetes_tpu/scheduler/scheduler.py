"""The scheduler daemon: watch wiring, the scheduleOne loop, and the batch seam.

Capability of ``plugin/pkg/scheduler/scheduler.go`` +
``factory/factory.go:120 NewConfigFactory``:

- informers feed the scheduler cache (bound/assumed pods, nodes) and the
  pending queue (unscheduled pods) — factory.go:140,188-199,391-520;
- ``schedule_one`` (scheduler.go:253): pop → snapshot → schedule → assume →
  bind, with failure → backoff re-enqueue (MakeDefaultErrorFunc,
  factory.go:718) and assumed-pod TTL expiry self-healing;
- Scheduled / FailedScheduling events (scheduler.go:174,248) and the three
  latency SLIs (metrics/metrics.go).

The TPU path: ``schedule_pending_batch`` drains the whole queue and hands
the batch to a pluggable ``backend`` (``kubernetes_tpu/ops/backend.py``),
generalizing the reference's 1-deep assume/bind pipeline (SURVEY.md P9) to
batch depth.  The oracle path stays available both as the correctness
reference and as the fallback when a batch member's bind CAS fails.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

from .. import faults
from ..api import lazy
from ..api import types as api
from ..client.clientset import BindConflictError, Clientset
from ..client.informer import Handler, InformerFactory
from ..client.record import EventBroadcaster
from ..store.store import ADDED, MODIFIED, NotFoundError
from ..utils import tracing
from ..utils.metrics import SchedulerMetrics
from ..utils.trace import Trace
from .generic_scheduler import FitError, GenericScheduler
from .nodeinfo import NodeInfo, SchedulerCache
from .priorities import PriorityContext
from .queue import PodBackoff, SchedulingQueue

logger = logging.getLogger("kubernetes_tpu.scheduler")

DEFAULT_SCHEDULER_NAME = "default-scheduler"

# memoized accelerator platform ("tpu" / "gpu" / "cpu" / "unknown"):
# _pipeline_idle's full-window polling gate reads it once per process
_ACCEL_PLATFORM: Optional[str] = None


def _accel_platform() -> str:
    global _ACCEL_PLATFORM
    if _ACCEL_PLATFORM is None:
        try:
            import jax

            _ACCEL_PLATFORM = jax.devices()[0].platform
        except Exception:
            _ACCEL_PLATFORM = "unknown"
    return _ACCEL_PLATFORM


def _poll_full_device_window() -> bool:
    """Should overlapped prep keep polling the device for the whole scan
    window?  A real accelerator (TPU/GPU) executes off the host CPU, so
    polling always hides in its shadow — poll unconditionally (ROADMAP
    open item: the old ``cpu_count > 1`` gate wrongly throttled 1-CPU
    TPU hosts).  On the XLA *CPU* "device" (or when the platform is
    unknown) the computation shares the host cores, and on a 1-core box
    every poll cycle stretches the scan 1:1 (measured 2x) — keep the
    spare-core requirement there."""
    import os

    platform = _accel_platform()
    if platform not in ("cpu", "unknown"):
        return True
    return (os.cpu_count() or 1) > 1


def _is_scheduler_pod(pod: api.Pod, name: str) -> bool:
    _, sched_name, phase = lazy.pod_brief(pod)
    return sched_name == name and phase in (api.PENDING, api.RUNNING)


class Scheduler:
    def __init__(
        self,
        clientset: Clientset,
        algorithm: Optional[GenericScheduler] = None,
        backend=None,
        scheduler_name: str = DEFAULT_SCHEDULER_NAME,
        assume_ttl: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        emit_events: bool = True,
        enable_preemption: bool = True,
    ):
        self.clientset = clientset
        self.algorithm = algorithm or GenericScheduler()
        self.backend = backend  # TPU batch backend (ops/backend.py) or None
        self.scheduler_name = scheduler_name
        self.cache = SchedulerCache(ttl=assume_ttl, clock=clock)
        self.queue = SchedulingQueue(clock=clock)
        self.backoff = PodBackoff(clock=clock)
        self.metrics = SchedulerMetrics()
        if backend is not None and hasattr(backend, "fallback_counter"):
            # kernel fallbacks surface in this scheduler's metrics registry
            backend.fallback_counter = self.metrics.pallas_fallback_total
        if backend is not None and hasattr(backend, "breaker_counter"):
            backend.breaker_counter = self.metrics.kernel_breaker_transitions
        if backend is not None and hasattr(backend, "frontier_counter"):
            backend.frontier_counter = self.metrics.frontier_compactions
        if backend is not None and hasattr(backend, "shed_counter"):
            backend.shed_counter = self.metrics.score_plane_sheds
        # overload control (ISSUE 17): a DegradationLadder wired via
        # attach_overload; None = full fidelity always
        self.overload = None
        self.emit_events = emit_events
        self.enable_preemption = enable_preemption
        self._clock = clock
        self._snapshot: dict[str, NodeInfo] = {}
        # steady-state pipeline: overlap the next wave's ingest (pump +
        # signature warming) with the current wave's device execution —
        # the cross-wave extension of the per-segment commit overlap.
        # False restores the lock-step behavior (the A/B seam).
        self.overlap_ingest = True
        self._last_prep_s = 0.0
        # per-wave phase split of the last schedule_pending_batch call
        # (bench.py's churn preset reports these per wave).  With tracing
        # enabled the tensorize/dispatch/device_wait/commit/prep keys are
        # DERIVED from the wave's span tree (same clock reads — the two
        # cannot disagree); disabled, they come from the backend's stats
        # deltas as before.
        self.last_batch_phases: dict = {}
        # attrs the batch loop stamps onto the NEXT wave's root span
        # (queue wait / accumulation window measured before the drain)
        self._wave_attrs_pending: dict = {}
        # async event pipeline (client-go tools/record): the hot path only
        # enqueues; correlation + store writes happen on the sink thread
        self.broadcaster = EventBroadcaster(
            clientset, source=scheduler_name, clock=clock
        )
        self._recorder = self.broadcaster.recorder("Pod")

        self.informers = InformerFactory(clientset)
        self._wire_informers()

    # -- informer wiring (factory.go:140-520) ------------------------------
    def _wire_informers(self) -> None:
        pods = self.informers.informer("Pod")
        pods.add_handler(
            Handler(
                on_add=self._on_pod_add,
                on_update=self._on_pod_update,
                on_delete=self._on_pod_delete,
                on_batch=self._on_pod_frame,
            )
        )
        nodes = self.informers.informer("Node")
        nodes.add_handler(
            Handler(
                on_add=lambda n: self.cache.add_node(n),
                on_update=lambda old, new: self.cache.update_node(new),
                on_delete=lambda n: self.cache.remove_node(n.meta.name),
            )
        )
        # services/replicasets: cache-only informers for spreading priorities;
        # PVs/PVCs: volume predicates (the reference wires 8 informers,
        # factory.go:120 — pods, nodes, PVs, PVCs, RCs, RSs, statefulsets,
        # services)
        self.informers.informer("Service")
        self.informers.informer("ReplicaSet")
        self.informers.informer("PersistentVolume")
        self.informers.informer("PersistentVolumeClaim")

    def _on_pod_add(self, pod: api.Pod) -> None:
        # pod_brief reads the routing fields (nodeName/schedulerName/
        # phase) straight off the wire dict for lazy events — the handler
        # fan-out never builds spec/status views for pods it only routes
        node_name, sched_name, phase = lazy.pod_brief(pod)
        if node_name:
            self.cache.add_pod(pod)
        elif sched_name == self.scheduler_name and phase in (api.PENDING,
                                                            api.RUNNING):
            self.queue.add(pod)

    def _on_pod_update(self, old: api.Pod, new: api.Pod) -> None:
        if lazy.pod_brief(new)[0]:
            if old is not None and lazy.pod_brief(old)[0]:
                self.cache.update_pod(old, new)
            else:
                self.queue.remove(new.meta.key)
                self.cache.add_pod(new)
        else:
            if _is_scheduler_pod(new, self.scheduler_name):
                self.queue.update(new)
            else:
                # pod became terminal (Failed/Succeeded) or changed scheduler
                # while pending: drop it from the queue
                self.queue.remove(new.meta.key)

    def _on_pod_delete(self, pod: api.Pod) -> None:
        if lazy.pod_brief(pod)[0]:
            self.cache.remove_pod(pod)
        else:
            self.queue.remove(pod.meta.key)

    def _on_pod_frame(self, frame, deltas) -> None:
        """Batch-aware pod routing (``Handler.on_batch``, ISSUE 6): one
        column-packed watch frame carries a whole correlated store txn.
        A bind-confirm frame (``bind_many``: all-MODIFIED, prev-revision
        column present) confirms the ENTIRE wave against the frame's
        identity/node/prev-revision columns in one cache lock hold —
        per-pod dict probes and containers compares collapse to integer
        compares (``SchedulerCache.confirm_many``).  Whatever the
        columnar fence rejects — and every non-confirm delta — takes the
        existing per-pod routing, so semantics are identical to per-event
        delivery by construction.

        The confirm span carries the emitting txn's correlation id
        (ISSUE 7) — the third hop of the store→informer→confirm trace."""
        tr = tracing.current()
        if tr is None:
            return self._route_pod_frame(frame, deltas)
        with tr.span("scheduler.confirm", cat="ingest", kind=frame.kind,
                     txn=frame.txn, events=len(deltas)) as sp:
            fb0 = self.metrics.confirm_fallbacks.value
            self._route_pod_frame(frame, deltas)
            sp.set(fallbacks=int(self.metrics.confirm_fallbacks.value - fb0))

    def _route_pod_frame(self, frame, deltas) -> None:
        self.metrics.watch_frames.inc()
        self.metrics.watch_frame_events.inc(len(deltas))
        rest = deltas
        prev = frame.prev_revisions
        if prev is not None:
            node_names = frame.node_names
            keys = frame.keys
            confirmable: list = []
            rest = []
            for d in deltas:
                etype, old, new, i = d
                if etype == MODIFIED and node_names[i]:
                    confirmable.append((keys[i], node_names[i], prev[i],
                                        new, old))
                else:
                    rest.append(d)
            if confirmable:
                # one queue lock + one cache lock for the whole wave
                self.queue.remove_many([c[0] for c in confirmable])
                for key, _node, _prev, new, old in self.cache.confirm_many(
                        confirmable):
                    # revision fence rejected it (no assumption, different
                    # node, or an intervening write): the per-pod compare
                    # path decides, exactly as per-event delivery would
                    self.metrics.confirm_fallbacks.inc()
                    self._on_pod_update(old, new)
        for etype, old, new, _i in rest:
            if etype == ADDED:
                self._on_pod_add(new)
            elif etype == MODIFIED:
                self._on_pod_update(old, new)
            else:
                self._on_pod_delete(old if old is not None else new)

    def start(self, manual: bool = True) -> None:
        """Seed informers.  manual=True (tests, bench) → caller pumps and
        events drain via ``broadcaster.flush()``; manual=False → informer
        threads run the watch loops and the event sink thread runs."""
        if manual:
            self.informers.start_all_manual()
        else:
            self.informers.start_all()
            if self.emit_events:
                self.broadcaster.start()

    def pump(self) -> int:
        tr = tracing.current()
        with (tr.span("ingest.pump", cat="ingest")
              if tr is not None else tracing.NULL_SPAN) as sp:
            n = self.informers.pump_all()
            if not self.broadcaster.running:
                # manual drive: no sink thread, so drain events synchronously
                self.broadcaster.flush()
            sp.set(events=n)
        return n

    def _ingest_decode_stats(self) -> tuple[float, int]:
        """(cumulative informer decode seconds, cumulative lazy
        promotions) across this scheduler's informers — per-wave deltas
        feed ``scheduler_ingest_decode_seconds`` and the churn bench."""
        from ..api import lazy as lazy_mod

        decode_s = sum(
            inf.stats.get("decode_s", 0.0)
            for inf in self.informers._informers.values())
        st = lazy_mod.STATS
        return decode_s, st["promotions"] + st["sections"]

    def _pump_apply_stats(self) -> tuple[float, int, int]:
        """(cumulative pump-application seconds, frames, frame events)
        across this scheduler's informers — per-wave deltas feed
        ``scheduler_pump_apply_seconds`` and the churn bench's
        pump-apply timers (ISSUE 6)."""
        apply_s = frames = frame_events = 0
        for inf in self.informers._informers.values():
            st = inf.stats
            apply_s += st.get("apply_s", 0.0)
            frames += st.get("frames", 0)
            frame_events += st.get("frame_events", 0)
        return apply_s, frames, frame_events

    def _observe_mesh_wave(self, lf, pre_shard, ncache, wave_span) -> None:
        """Per-shard SLO attribution of the sharded wave loop (the PR-12
        caveat lands here: an AGGREGATE upload fraction hides one cold
        shard behind N-1 warm ones).  The worst shard's upload fraction
        and the alive-fraction skew land on literal-named gauges —
        ``utils.slo.mesh_slos`` windows them — and the per-shard lists
        ride the existing wave span as one ``mesh`` attr, not a second
        trace format."""
        mesh_segs = [s for s in (lf or []) if s.get("mode") == "mesh"]
        if not mesh_segs:
            return
        n_shards = max(int(s.get("n_shards", 0)) for s in mesh_segs)
        self.metrics.mesh_shards.set(n_shards)
        attrs: dict = {"n_shards": n_shards}
        skews = [max(fr) - min(fr) for s in mesh_segs
                 for fr in (s.get("shard_alive_frac") or []) if fr]
        if skews:
            skew = round(max(skews), 4)
            self.metrics.mesh_shard_alive_skew.set(skew)
            attrs["shard_alive_skew"] = skew
        if pre_shard is not None and ncache is not None:
            dirty = ncache.stats.get("shard_dirty_cols", ())
            cols = ncache.stats.get("shard_cols_total", ())
            # first mesh wave: set_mesh() sized the per-shard counters
            # AFTER the pre-wave capture — an empty pre-list means zero
            pre_d = pre_shard[0] or (0,) * len(dirty)
            pre_c = pre_shard[1] or (0,) * len(cols)
            fracs = []
            if len(dirty) == len(pre_d) and len(cols) == len(pre_c):
                for d0, d1, c0, c1 in zip(pre_d, dirty, pre_c, cols):
                    if c1 - c0 > 0:
                        fracs.append((d1 - d0) / (c1 - c0))
            if fracs:
                worst = round(max(fracs), 4)
                self.metrics.mesh_worst_shard_upload_fraction.set(worst)
                attrs["shard_upload_fractions"] = [round(f, 4) for f in fracs]
                attrs["worst_shard_upload_fraction"] = worst
        self.last_batch_phases["mesh"] = attrs
        if wave_span is not None:
            wave_span.set(mesh=attrs)

    # -- snapshot ----------------------------------------------------------
    def snapshot(self) -> dict[str, NodeInfo]:
        """Generation-checked CoW refresh (cache.go:79)."""
        self.cache.snapshot_into(self._snapshot)
        return self._snapshot

    def _volume_listers(self) -> tuple[dict, dict]:
        """(pvs by name, pvcs by namespaced key) — shared by scheduling and
        preemption so both resolve claims identically."""
        pvs = {pv.meta.name: pv for pv in self.informers.informer("PersistentVolume").list()}
        pvcs = {pvc.meta.key: pvc for pvc in self.informers.informer("PersistentVolumeClaim").list()}
        return pvs, pvcs

    def priority_context(self, snapshot: dict[str, NodeInfo]) -> PriorityContext:
        services = self.informers.informer("Service").list()
        replicasets = self.informers.informer("ReplicaSet").list()
        pvs, pvcs = self._volume_listers()
        return PriorityContext(
            snapshot, services=services, replicasets=replicasets, pvcs=pvcs, pvs=pvs
        )

    # -- events / SLIs -----------------------------------------------------
    def _event(self, pod: api.Pod, etype: str, reason: str, message: str) -> None:
        if not self.emit_events:
            return
        self._recorder.event(pod, etype, reason, message)

    # -- bind + failure handling ------------------------------------------
    def _requeue_after_bind_failure(self, pod: api.Pod) -> None:
        """Transient bind failures re-enqueue the pod with backoff.

        Without this a pod whose bind hit a transport/store error was
        stranded: popped from the queue, never bound, and no watch event
        would ever re-add it.  Re-enqueues the LATEST informer version
        (like handle_schedule_failure) and only while the pod is still
        ours to place — a pod that meanwhile got bound or turned terminal
        belongs to whoever did that."""
        latest = self.informers.informer("Pod").get(pod.meta.key)
        if latest is None:
            return  # deleted while the bind was in flight
        if latest.spec.node_name or not _is_scheduler_pod(latest, self.scheduler_name):
            return  # bound by someone else, or became terminal
        self.metrics.bind_requeues.inc()
        # a decided placement that did not land: flight-recorder trigger
        tracing.notify_requeue(pod.meta.key)
        self.queue.add_after(latest, self.backoff.get_backoff(pod.meta.key))

    def _bind(self, pod: api.Pod, node_name: str) -> bool:
        tr = tracing.current()
        with (tr.span("scheduler.bind", cat="bind", pod=pod.meta.key,
                      node=node_name)
              if tr is not None else tracing.NULL_SPAN):
            return self._bind_attempt(pod, node_name)

    def _bind_attempt(self, pod: api.Pod, node_name: str) -> bool:
        start = self._clock()
        try:
            faults.hit("scheduler.bind", pod=pod.meta.key, node=node_name,
                       via="bind")
            self.clientset.pods.bind(
                api.Binding(
                    pod_namespace=pod.meta.namespace, pod_name=pod.meta.name, node_name=node_name
                )
            )
        except (BindConflictError, NotFoundError) as e:
            # permanent for THIS placement: the pod's fate is owned
            # elsewhere (already bound / deleted) — the informer stream
            # delivers the truth, nothing to retry
            logger.warning("bind failed for %s: %s", pod.meta.key, e)
            self.metrics.bind_failures.inc()
            self.cache.forget_pod(pod)
            self._event(pod, "Warning", "FailedBinding", str(e))
            return False
        except Exception as e:
            # transient (transport error, apiserver overload, injected
            # fault): the placement decision may still be right — drop
            # the assumption and retry the pod with backoff
            logger.warning("transient bind failure for %s: %s: %s",
                           pod.meta.key, type(e).__name__, e)
            self.metrics.bind_failures.inc()
            self.cache.forget_pod(pod)
            self._event(pod, "Warning", "FailedBinding", str(e))
            self._requeue_after_bind_failure(pod)
            return False
        self.metrics.binding_latency.observe((self._clock() - start) * 1e6)
        self.cache.finish_binding(pod.meta.key)
        self._event(pod, "Normal", "Scheduled", f"Successfully assigned {pod.meta.key} to {node_name}")
        return True

    def handle_schedule_failure(self, pod: api.Pod, err: Exception,
                                ev_batch: Optional[list] = None,
                                preempt_cohort: Optional[list] = None) -> None:
        """MakeDefaultErrorFunc (factory.go:718): re-enqueue with backoff.

        Re-enqueues the *latest* version from the informer cache, not the
        popped object — a spec patch that landed while the pod was in
        flight (e.g. adding the missing toleration) must not be lost.

        For priority pods, tries preemption first (the PostFilter phase):
        evicting a minimal set of lower-priority victims and requeueing the
        preemptor without backoff into the freed space.

        ``ev_batch``: batch callers pass a list to collect the
        FailedScheduling event instead of enqueueing (and waking the sink)
        per pod mid-batch.  ``preempt_cohort``: batch callers pass a list
        to DEFER priority pods' preemption to one cohort pass after the
        drain (``_preempt_cohort``) — the prefilter kernel then amortizes
        over the whole cohort instead of sweeping every node per pod."""
        self.metrics.schedule_failures.inc()
        if ev_batch is not None and self.emit_events:
            ev_batch.append((pod, "Warning", "FailedScheduling", str(err)))
        else:
            self._event(pod, "Warning", "FailedScheduling", str(err))
        latest = self.informers.informer("Pod").get(pod.meta.key)
        if latest is None:
            return  # deleted while we were scheduling it
        if latest.spec.node_name or not _is_scheduler_pod(latest, self.scheduler_name):
            return  # bound by someone else, or became terminal
        if self.enable_preemption and latest.spec.priority > 0:
            # overload ladder (ISSUE 17): at rung >= 2 the batched
            # PostFilter pass is reserved for the critical tier — lower
            # tiers take the plain backoff requeue below, so top-tier
            # preemption work is never diluted by standard-tier churn
            ov = self.overload
            if (ov is not None
                    and ov.classifier.tier_of(latest) < ov.preempt_tier_floor):
                self.metrics.preemption_sheds.inc()
            elif preempt_cohort is not None:
                preempt_cohort.append(latest)  # requeue decided at cohort time
                return
            elif self._try_preempt(latest):
                self.queue.add(latest)  # victims evicted; retry immediately
                return
        delay = self.backoff.get_backoff(pod.meta.key)
        self.queue.add_after(latest, delay)

    def _evict_victims(self, pod: api.Pod, target, ev_batch: Optional[list] = None) -> None:
        for victim in target.victims:
            try:
                self.clientset.pods.delete(victim.meta.name, victim.meta.namespace)
                self.metrics.preemption_victims.inc()
                msg = (f"Preempted by {pod.meta.key} (priority "
                       f"{pod.spec.priority}) on {target.node_name}")
                if ev_batch is not None and self.emit_events:
                    ev_batch.append((victim, "Normal", "Preempted", msg))
                else:
                    self._event(victim, "Normal", "Preempted", msg)
            except NotFoundError:
                continue

    def _try_preempt(self, pod: api.Pod) -> bool:
        from .preemption import find_preemption_target

        start = self._clock()
        self.metrics.preemption_attempts.inc()
        pvs, pvcs = self._volume_listers()
        target = find_preemption_target(
            pod, self.snapshot(), self.algorithm.predicates, pvcs=pvcs, pvs=pvs
        )
        if target is None:
            self.metrics.preemption_latency.observe((self._clock() - start) * 1e6)
            return False
        self._evict_victims(pod, target)
        self.pump()  # observe the deletions so the next attempt sees freed space
        self.metrics.preemption_latency.observe((self._clock() - start) * 1e6)
        return True

    def _preempt_cohort(self, cohort: list, ev_batch: Optional[list] = None) -> int:
        """Batch-path PostFilter (SURVEY §7.4.7): one prefilter-kernel call
        bounds every (preemptor, node) pair's victim cost; the exact
        reprieve evaluation then runs only on nodes whose bound can win
        (``find_preemption_target_fast`` — decisions identical to the
        per-pod oracle on the same state by construction).  Preemptors are
        processed in batch order; each eviction updates the state columns
        of the touched node so later preemptors see the new truth.
        Returns the number of successful preemptions; every cohort pod is
        requeued (immediately on success, with backoff otherwise)."""
        from ..ops.preemption_kernel import PreemptionState
        from .preemption import _fast_eligible, find_preemption_target_fast
        from .units import pod_request_vec

        if not cohort:
            return 0
        from ..models.snapshot import pod_signature_key

        snapshot = self.snapshot()
        pvs, pvcs = self._volume_listers()
        state = PreemptionState(snapshot)
        touched: set[str] = set()
        # node-static predicate gate memo per preemptor SIGNATURE (the
        # gate is victim-independent and generation-checked inside
        # find_preemption_target_fast, so same-template preemptors pay
        # it once per node across the whole cohort)
        static_caches: dict = {}
        preempted = 0
        # fits-now recheck state: shadow clones of earlier-eviction
        # targets (the ONLY nodes that can have become feasible since the
        # batch proved these pods unschedulable).  ``claims`` carries
        # every cohort member already promised capacity on a node —
        # evictors and fits-now grantees alike — and shadows are rebuilt
        # as fresh-state-plus-claims, so a SECOND eviction on the same
        # node never drops earlier claimants.  Capped: a huge touched
        # set degrades the recheck to best-effort-off.
        recheck_shadow: dict[str, NodeInfo] = {}
        claims: dict[str, list] = {}
        recheck_cap = 64
        for pod in cohort:
            start = self._clock()
            self.metrics.preemption_attempts.inc()
            latest = self.informers.informer("Pod").get(pod.meta.key)
            if latest is None:
                continue  # deleted while deferred
            if latest.spec.node_name or not _is_scheduler_pod(latest, self.scheduler_name):
                continue
            cands: list = []
            if not _fast_eligible(latest, self.algorithm.predicates):
                # odd preemptors (ports/volumes/own required affinity /
                # custom predicate set) take the branch-and-bound path,
                # which needs the prefilter bounds; the fast vectorized
                # path derives everything from `state` directly
                cands = state.candidates_for(
                    pod_request_vec(latest).units, latest.spec.priority)
            target = find_preemption_target_fast(
                latest, snapshot, cands, self.algorithm.predicates,
                pvcs=pvcs, pvs=pvs,
                static_cache=static_caches.setdefault(
                    pod_signature_key(latest), {}),
                state=state,
                recheck_nodes=sorted(recheck_shadow.items())
                if 0 < len(recheck_shadow) <= recheck_cap else None)
            if target is None:
                self.metrics.preemption_latency.observe(
                    (self._clock() - start) * 1e6)
                delay = self.backoff.get_backoff(pod.meta.key)
                self.queue.add_after(latest, delay)
                continue
            if not target.victims:
                # an earlier cohort eviction already freed space this pod
                # provably fits into — no eviction, retry immediately;
                # record the claim so later cohort members see it taken
                claims.setdefault(target.node_name, []).append(latest)
                shadow = recheck_shadow.get(target.node_name)
                if shadow is not None:
                    shadow.add_pod(latest)
                self.queue.add(latest)
                self.metrics.preemption_latency.observe(
                    (self._clock() - start) * 1e6)
                continue
            self._evict_victims(latest, target, ev_batch)
            self.pump()  # observe deletions: cache + informers advance
            snapshot = self.snapshot()
            fresh = snapshot.get(target.node_name)
            state.update_node(target.node_name, fresh)
            claims.setdefault(target.node_name, []).append(latest)
            if fresh is not None:
                # shadow = post-eviction state PLUS every outstanding
                # claim on this node (earlier grantees/evictors retry
                # into this space next batch) — later cohort members
                # must not be granted already-promised capacity
                shadow = fresh.clone()
                for claimant in claims[target.node_name]:
                    shadow.add_pod(claimant)
                recheck_shadow[target.node_name] = shadow
            touched.add(target.node_name)
            preempted += 1
            self.queue.add(latest)  # retry immediately into the freed space
            self.metrics.preemption_latency.observe((self._clock() - start) * 1e6)
        return preempted

    # -- the per-pod oracle loop (scheduler.go:253) ------------------------
    def schedule_one(self, timeout: Optional[float] = 0.0, async_bind: bool = False) -> bool:
        pod = self.queue.pop(timeout=timeout)
        if pod is None:
            return False
        start = self._clock()
        trace = Trace(f"Scheduling {pod.meta.key}", clock=self._clock)
        self.metrics.schedule_attempts.inc()
        snapshot = self.snapshot()
        trace.step("snapshot")
        try:
            algo_start = self._clock()
            result = self.algorithm.schedule(pod, snapshot, self.priority_context(snapshot))
            self.metrics.scheduling_algorithm_latency.observe((self._clock() - algo_start) * 1e6)
        except FitError as e:
            self.handle_schedule_failure(pod, e)
            return True
        trace.step("schedule")
        self.cache.assume_pod(pod, result.node_name)
        self.backoff.forget(pod.meta.key)
        if async_bind:
            threading.Thread(target=self._bind, args=(pod, result.node_name), daemon=True).start()
        else:
            self._bind(pod, result.node_name)
        trace.step("bind")
        self.metrics.e2e_scheduling_latency.observe((self._clock() - start) * 1e6)
        trace.log_if_long(0.1)
        return True

    def run_pending(self, max_pods: Optional[int] = None, pump_every: int = 100) -> int:
        """Drive schedule_one until the queue drains (test/bench harness)."""
        n = 0
        while (max_pods is None or n < max_pods) and len(self.queue) > 0:
            if not self.schedule_one(timeout=0.0):
                break
            n += 1
            if n % pump_every == 0:
                self.pump()
        self.pump()
        return n

    # -- the steady-state pipeline -----------------------------------------
    def _pipeline_idle(self, device_busy: Optional[Callable[[], bool]] = None) -> None:
        """Cross-wave overlapped prep, run by the backend in the shadow of
        the final segment's device execution: pump the informers (so the
        next wave's arrivals, node updates, and our own earlier bind
        confirmations are already digested when the drain happens) and
        warm the per-pod signature/content memos of everything queued.
        With a ``device_busy`` probe, prep keeps pumping until the device
        finishes — the whole scan window becomes ingest time instead of a
        blocked finalize.

        Touches only informers, cache, and queue — never the snapshot the
        in-flight batch was tensorized from, so the current wave's
        decisions are already fixed and parity is unaffected.  A failure
        here (including the injected ``scheduler.pipeline.prep`` fault)
        is contained: the work re-runs synchronously at the next wave's
        start, which is exactly the unpipelined behavior."""
        import time as _time

        t0 = _time.perf_counter()
        # Full-window polling is gated by PLATFORM (ROADMAP open item): a
        # real accelerator executes off the host CPU, so prep always hides
        # in its shadow; only the XLA CPU "device" — which shares the host
        # cores — still requires a spare core (on a 1-core box every poll
        # cycle stretched the scan 1:1, measured 2x).
        poll = device_busy is not None and _poll_full_device_window()
        try:
            faults.hit("scheduler.pipeline.prep")
            from ..models.snapshot import _pod_content_key, pod_signature_key

            while True:
                self.pump()
                for pod in self.queue.snapshot_pending():
                    # the wave's decode work, spread into the idle shadow:
                    # on the lazy path these are raw-dict reads (columns),
                    # never full object decodes — the drain then finds
                    # every per-pod memo warm
                    pod_signature_key(pod)
                    _pod_content_key(pod)
                if not poll or not device_busy():
                    break
                _time.sleep(0.002)
        except Exception as e:
            self.metrics.pipeline_prep_failures.inc()
            logger.warning("overlapped prep failed (work deferred to the "
                           "next wave): %s: %s", type(e).__name__, e)
        finally:
            t_end = _time.perf_counter()
            self._last_prep_s = t_end - t0
            self.metrics.pipeline_prep_latency.observe(self._last_prep_s * 1e6)
            tr = tracing.current()
            if tr is not None:
                # the overlapped host prep, attributed inside the wave's
                # device shadow (same clock reads as _last_prep_s)
                tr.complete("prep", t0, t_end, cat="phase", polled=poll)

    # -- overload control (ISSUE 17) ---------------------------------------
    def attach_overload(self, ladder) -> None:
        """Wire a ``utils.overload.DegradationLadder``: its rung lands in
        this scheduler's gauge/counter, and the batch loop consults it
        every iteration for effective accumulation knobs, score-plane
        shedding, and the preemption tier floor."""
        self.overload = ladder
        ladder.gauge = self.metrics.degradation_rung
        ladder.transition_counter = self.metrics.degradation_transitions

    def _apply_overload_knobs(self) -> None:
        """Push the ladder's rung-1/2 knobs onto the backend before a
        wave: score-plane shedding and sticky-bucket coarsening.  Cheap
        and idempotent — called once per wave."""
        ov = self.overload
        if ov is None or self.backend is None:
            return
        if hasattr(self.backend, "shed_score_planes"):
            self.backend.shed_score_planes = ov.shed_score_planes
        tz = getattr(self.backend, "tensorizer", None)
        if tz is not None and hasattr(tz, "bucket_scale"):
            tz.bucket_scale = ov.bucket_scale

    def _top_tier_ready(self) -> bool:
        """True when a critical-tier pod is waiting in the queue — under
        overload the accumulation window breaks early for it (the top
        tier never waits the widened window).  O(pending) scan; callers
        rate-limit it."""
        ov = self.overload
        if ov is None:
            return False
        cls = ov.classifier
        for pod in self.queue.snapshot_pending():
            if cls.tier_of(pod) >= cls.CRITICAL:
                return True
        return False

    def run_batch_loop(
        self,
        min_batch: int = 1,
        max_wait: float = 0.05,
        idle_timeout: Optional[float] = None,
        max_waves: Optional[int] = None,
        poll_interval: float = 0.005,
        max_batch: Optional[int] = None,
        stop: Optional[threading.Event] = None,
    ) -> int:
        """Continuous service mode: drain-and-schedule as pods arrive,
        under a min-batch/max-wait accumulation policy, until the queue
        is closed (or ``stop`` is set, or ``idle_timeout``/``max_waves``
        ends the loop).

        Each iteration pumps the informers (a no-op when watch threads
        own the streams), waits until at least ``min_batch`` pods are
        ready or ``max_wait`` has elapsed since the first ready pod (the
        queue-wait SLI records the window), and runs one pipelined wave.
        ``queue.close()`` unblocks the accumulation wait and ends the
        loop.  Returns total pods bound."""
        bound_total = 0
        waves = 0

        def stopped() -> bool:
            return self.queue.closed or (stop is not None and stop.is_set())

        idle_deadline = (self._clock() + idle_timeout
                         if idle_timeout is not None else None)
        while not stopped() and (max_waves is None or waves < max_waves):
            self.pump()
            ready = len(self.queue)
            self.metrics.pending_pods.set(float(ready))
            if ready == 0:
                if idle_deadline is not None and self._clock() >= idle_deadline:
                    break
                self.queue.wait_ready(timeout=poll_interval)
                continue
            # overload ladder (ISSUE 17): knobs are re-read every
            # iteration, so a rung change takes effect on the NEXT wave
            # without restarting the loop
            ov = self.overload
            eff_min_batch, eff_max_wait = min_batch, max_wait
            if ov is not None:
                ov.poll()
                eff_min_batch, eff_max_wait = ov.batch_knobs(min_batch, max_wait)
            t_first = self._clock()
            tier_check_at = t_first  # rate-limits the O(pending) tier scan
            while (ready < eff_min_batch and not stopped()
                   and self._clock() - t_first < eff_max_wait):
                # plain sleep, NOT wait_ready: something is already ready
                # (that's how we got here), so wait_ready would return
                # immediately and turn the accumulation window into a
                # 100% busy-spin of pump()+len()
                time.sleep(poll_interval)
                self.pump()
                ready = len(self.queue)
                if ov is not None and ov.rung >= 1:
                    now = self._clock()
                    if now >= tier_check_at:
                        tier_check_at = now + 0.025
                        if self._top_tier_ready():
                            break  # critical pods never wait the widened window
            queue_wait = self._clock() - t_first
            self.metrics.batch_queue_wait.observe(queue_wait * 1e6)
            self.metrics.pending_pods.set(float(ready))
            # the accumulation window rides onto the next wave's root
            # span (ISSUE 7): queue wait + how many pods the window
            # gathered vs the min-batch target
            self._wave_attrs_pending = {
                "queue_wait_s": round(queue_wait, 6),
                "accumulated": ready, "min_batch": eff_min_batch}
            if ov is not None:
                self._wave_attrs_pending["overload_rung"] = ov.rung
            bound, _ = self.schedule_pending_batch(max_batch)
            bound_total += bound
            waves += 1
            idle_deadline = (self._clock() + idle_timeout
                             if idle_timeout is not None else None)
        return bound_total

    # -- the batch TPU path ------------------------------------------------
    def schedule_pending_batch(self, max_batch: Optional[int] = None) -> tuple[int, int]:
        """Drain the queue, schedule the whole batch on the backend, then
        assume+bind each result in pod order.  Returns (bound, failed)."""
        if self.backend is None:
            raise RuntimeError("no batch backend configured")
        pods = self.queue.drain(max_batch)
        if not pods:
            return (0, 0)
        self._apply_overload_knobs()
        self.metrics.batch_size.observe(len(pods))
        tr = tracing.current()
        # Cyclic GC is paused for the whole batch (tensorize + kernel +
        # commit): at 150k pods a collection pass walks millions of live
        # objects and costs more than everything it frees (the Go
        # reference has a concurrent GC; Python's stop-the-world pass
        # must not land inside the hot loop).
        import gc as _gc

        gc_was_enabled = _gc.isenabled()
        _gc.disable()
        totals = {"bound": 0, "failed": 0, "committed": 0,
                  "attempted_binds": 0, "commit_s": 0.0}
        # ONE event enqueue for the whole batch, after the last commit:
        # enqueueing per segment would wake the sink thread mid-batch and
        # its correlation/store writes would steal the GIL from the host
        # phases that are NOT in the device's shadow (tensorize/apply)
        ev_batch: list = []
        # priority pods whose scheduling failed: preemption is deferred to
        # ONE cohort pass after the drain (see _preempt_cohort)
        preempt_cohort: list = [] if self.enable_preemption else None

        def commit_segment(entries: list) -> None:
            """Assume + bind + record one segment's results (the batch
            generalization of the reference's async-bind pipeline,
            SURVEY.md P9, now streamed per segment: the backend invokes
            this while the device executes the NEXT segment, so the
            commit cost hides in the scan's shadow)."""
            t_commit = time.perf_counter()
            to_bind: list[tuple[api.Pod, api.Binding]] = []
            to_assume: list[tuple] = []
            for pod, node_name, req_vec, nz_vec in entries:
                if node_name is None:
                    self.handle_schedule_failure(pod, FitError(pod, {}), ev_batch,
                                                 preempt_cohort=preempt_cohort)
                    totals["failed"] += 1
                    continue
                # per-signature request vectors from the backend (when the
                # kernel path produced this entry) spare the cache assume
                # a per-pod quantity re-parse
                to_assume.append((pod, node_name, req_vec, nz_vec))
                self.backoff.forget(pod.meta.key)
                to_bind.append(
                    (
                        pod,
                        api.Binding(
                            pod_namespace=pod.meta.namespace,
                            pod_name=pod.meta.name,
                            node_name=node_name,
                        ),
                    )
                )
            self.cache.assume_many(to_assume)
            bind_start = self._clock()
            try:
                errors = self.clientset.pods.bind_many([b for _, b in to_bind])
            except Exception as e:
                # the whole segment's commit failed before any CAS applied
                # (store overload / transport outage / injected fault):
                # nothing bound — every entry takes the per-item failure
                # path below, which forgets the assumption and requeues
                logger.warning("bind_many failed for %d pods: %s: %s",
                               len(to_bind), type(e).__name__, e)
                errors = [f"transient: {e}"] * len(to_bind)
            self.metrics.binding_latency.observe((self._clock() - bind_start) * 1e6)
            finished: list[str] = []
            emit = self.emit_events
            for (pod, binding), err in zip(to_bind, errors):
                if err is None:
                    finished.append(pod.meta.key)
                    if emit:
                        ev_batch.append((
                            pod, "Normal", "Scheduled",
                            ("Successfully assigned %s to %s",
                             pod.meta.key, binding.node_name),
                        ))
                    totals["bound"] += 1
                else:
                    logger.warning("bind failed for %s: %s", pod.meta.key, err)
                    self.metrics.bind_failures.inc()
                    self.cache.forget_pod(pod)
                    if emit:
                        ev_batch.append((pod, "Warning", "FailedBinding", err))
                    # requeue-with-backoff when the pod is still ours and
                    # unbound (transient CAS/transport failure) — decided
                    # from the informer's latest truth, so a genuine
                    # conflict (bound elsewhere) is NOT retried
                    self._requeue_after_bind_failure(pod)
                    totals["failed"] += 1
            self.cache.finish_binding_many(finished)
            totals["committed"] += len(finished)
            totals["attempted_binds"] += len(to_bind)
            # per-segment e2e SLI: pods committed in segment s of S were
            # bound NOW, at this point of the drain — not at batch end.
            # One observe_many per segment keeps p50/p99 distinct without
            # per-pod lock rounds (the reference's three SLIs are per-pod
            # for exactly this reason, metrics/metrics.go:26-50)
            self.metrics.e2e_scheduling_latency.observe_many(
                (self._clock() - start) * 1e6, len(to_bind))
            t_commit_end = time.perf_counter()
            totals["commit_s"] += t_commit_end - t_commit
            if tr is not None:
                # same two clock reads feed the stats timer and the span:
                # the trace-derived commit_s below IS this measurement
                tr.complete("commit", t_commit, t_commit_end, cat="phase",
                            pods=len(entries), bound=len(finished))

        # phase accounting for the churn bench: deltas of the backend's
        # cumulative timers bracket this batch's tensorize/device split
        bstats = getattr(self.backend, "stats", None)
        phase_keys = ("tensorize_s", "dispatch_s", "device_wait_s")
        pre_phases = ({k: bstats.get(k, 0.0) for k in phase_keys}
                      if isinstance(bstats, dict) else None)
        # blocking device→host round-trips: same pre/post-delta seam as
        # the phase timers (the device-resident loop drives this to
        # O(compactions + 1) per wave; the chunked host loop is O(chunks))
        pre_syncs = (bstats.get("host_syncs", 0)
                     if isinstance(bstats, dict) else None)
        ncache = getattr(self.backend, "device_node_cache", None)
        pre_cols = ((ncache.stats["dirty_cols"], ncache.stats["cols_total"],
                     ncache.stats["reuses"])
                    if ncache is not None else None)
        # per-shard upload accounting (mesh mode): snapshot the per-shard
        # cumulative counters so the wave delta attributes dirty columns
        # to the shard that received them
        pre_shard = ((tuple(ncache.stats.get("shard_dirty_cols", ())),
                      tuple(ncache.stats.get("shard_cols_total", ())))
                     if ncache is not None else None)
        pre_decode = self._ingest_decode_stats()
        pre_apply = self._pump_apply_stats()
        pre_fallbacks = self.metrics.confirm_fallbacks.value
        self._last_prep_s = 0.0
        extra = {}
        if self.overlap_ingest:
            # checked per call: tests swap schedule_batch for wrappers
            # that predate the on_idle seam
            import inspect

            try:
                if "on_idle" in inspect.signature(
                        self.backend.schedule_batch).parameters:
                    extra["on_idle"] = self._pipeline_idle
            except (TypeError, ValueError):
                pass

        # one span tree per wave (ISSUE 7): everything this thread does
        # for the batch — tensorize, segment dispatch/finalize, frontier
        # chunks, commits, overlapped prep, ingest pumps — nests under
        # this root; closed (and pushed into the flight-recorder ring)
        # in the finally below.  Entered immediately before the try so
        # no exception path can leak an open root on the span stack
        # (a leaked root would adopt every later wave as a child).
        wave_cm = wave_span = None
        if tr is not None:
            wave_cm = tr.wave(pods=len(pods), **self._wave_attrs_pending)
            self._wave_attrs_pending = {}
            wave_span = wave_cm.__enter__()
        try:
            start = self._clock()
            snapshot = self.snapshot()
            pctx = self.priority_context(snapshot)
            algo_start = self._clock()
            self.backend.schedule_batch(pods, snapshot, pctx,
                                        on_segment=commit_segment, **extra)
            # wall time of the whole batch dispatch: on the kernel path the
            # per-segment commits run concurrently with the device scan and
            # hide in its shadow (subtracting them would under-report device
            # time); on the oracle fallback and for the final segment the
            # commit is serial and IS part of the batch wall time.
            # binding_latency isolates the commit cost either way
            self.metrics.batch_device_latency.observe(
                (self._clock() - algo_start) * 1e6)
            self.metrics.schedule_attempts.inc(len(pods))
            if preempt_cohort:
                # PostFilter: one prefilter-kernel pass over the failed
                # priority pods, exact victim selection on the survivors
                self._preempt_cohort(preempt_cohort, ev_batch)
            bound, failed = totals["bound"], totals["failed"]
            if pre_phases is not None:
                self.last_batch_phases = {
                    k: bstats.get(k, 0.0) - pre_phases[k] for k in phase_keys
                }
                self.last_batch_phases["commit_s"] = totals["commit_s"]
                self.last_batch_phases["prep_s"] = self._last_prep_s
                self.metrics.pipeline_device_wait.observe(
                    self.last_batch_phases["device_wait_s"] * 1e6)
            if pre_syncs is not None:
                wave_syncs = int(bstats.get("host_syncs", 0) - pre_syncs)
                self.last_batch_phases["host_syncs"] = wave_syncs
                if wave_syncs > 0:
                    self.metrics.host_syncs.inc(wave_syncs)
                if wave_span is not None:
                    wave_span.set(host_syncs=wave_syncs)
            # ingest-decode split of the wave (ISSUE 4): informer decode
            # seconds + lazy promotions since the last snapshot — the
            # churn bench's pump-phase companion timers
            post_decode = self._ingest_decode_stats()
            decode_s = post_decode[0] - pre_decode[0]
            promos = post_decode[1] - pre_decode[1]
            self.last_batch_phases["decode_s"] = decode_s
            self.last_batch_phases["promotions"] = promos
            if wave_span is not None:
                wave_span.set(decode_s=round(decode_s, 6), promotions=promos)
            self.metrics.ingest_decode_seconds.observe(decode_s)
            if promos > 0:
                self.metrics.ingest_promotions.inc(promos)
            # pump APPLICATION split of the wave (ISSUE 6): informer
            # cache-apply + handler fan-out (incl. the columnar bind
            # confirm) time, frame volume, and confirm fallbacks
            post_apply = self._pump_apply_stats()
            apply_s = post_apply[0] - pre_apply[0]
            frames = post_apply[1] - pre_apply[1]
            frame_events = post_apply[2] - pre_apply[2]
            self.last_batch_phases["apply_s"] = apply_s
            self.last_batch_phases["frames"] = frames
            self.last_batch_phases["frame_events"] = frame_events
            self.last_batch_phases["confirm_fallbacks"] = int(
                self.metrics.confirm_fallbacks.value - pre_fallbacks)
            self.metrics.pump_apply_seconds.observe(apply_s)
            if wave_span is not None:
                wave_span.set(apply_s=round(apply_s, 6), frames=frames,
                              frame_events=frame_events)
            if pre_cols is not None:
                dirty = ncache.stats["dirty_cols"] - pre_cols[0]
                cols = ncache.stats["cols_total"] - pre_cols[1]
                if cols > 0:
                    self.metrics.tensorize_upload_fraction.observe(dirty / cols)
                    if wave_span is not None:
                        # tensorize attribution: dirty-column diff volume
                        # and the upload fraction of the node axis
                        wave_span.set(dirty_cols=dirty, cols_total=cols,
                                      upload_fraction=round(dirty / cols, 4))
            # frontier trajectory of this wave (per-segment prefilter
            # widths, alive-union fractions, compactions) for the bench
            lf = getattr(self.backend, "last_frontier", None)
            if lf:
                self.last_batch_phases["frontier"] = [dict(seg) for seg in lf]
                if wave_span is not None:
                    wave_span.set(frontier=[dict(seg) for seg in lf])
                for seg in lf:
                    fr = seg.get("alive_frac") or []
                    if fr:
                        self.metrics.frontier_alive_fraction.observe(min(fr))
            self._observe_mesh_wave(lf, pre_shard, ncache, wave_span)
        finally:
            if wave_cm is not None:
                wave_span.set(bound=totals["bound"], failed=totals["failed"],
                              committed=totals["committed"])
                wave_cm.__exit__(None, None, None)
                # derive the phase split FROM the wave's span tree: the
                # spans were fed by the very same clock reads as the
                # stats timers, so the dict and the exported trace can
                # never disagree
                self.last_batch_phases.update(wave_span.phase_totals())
            if gc_was_enabled:
                _gc.enable()
            # committed segments' events must survive a mid-batch failure —
            # their pods ARE bound in the cluster
            if ev_batch:
                self._recorder.event_batch(ev_batch)
        if self.emit_events and not self.broadcaster.running:
            # manual drive (no sink thread): drain synchronously so the
            # batch path's events land just like the per-pod path's
            self.broadcaster.flush()
        return (bound, failed)

    # -- housekeeping ------------------------------------------------------
    def cleanup(self) -> list[str]:
        return self.cache.cleanup_expired()
