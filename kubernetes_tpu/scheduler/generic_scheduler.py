"""The oracle scheduling algorithm: filter → score → select.

Capability of ``plugin/pkg/scheduler/core/generic_scheduler.go``:
``Schedule :88`` = snapshot → ``findNodesThatFit :163`` →
``PrioritizeNodes :285`` → ``selectHost :144``.

This is the sequential-greedy CPU oracle the TPU batch backend must match
binding-for-binding.  Its determinism spec (shared with the kernels):

- nodes are evaluated in **sorted-by-name order** (the canonical node axis
  order, also the tensor row order);
- ``select_host`` breaks score ties round-robin with a persistent counter
  over the tied nodes in node-axis order (reference ``lastNodeIndex``);
- all scores are fixed-point integers (see ``priorities.py``), so
  argmax+tiebreak is exact on both paths.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..api import types as api
from .nodeinfo import NodeInfo
from .predicates import (
    DEFAULT_PREDICATES,
    PredicateContext,
    compute_metadata,
    fast_fit_nodes,
    pod_fits_on_node,
)
from .priorities import PriorityContext, default_priorities


class FitError(Exception):
    """No node fits (reference core/generic_scheduler.go:46 FitError)."""

    def __init__(self, pod: api.Pod, failed_predicates: dict[str, list[str]]):
        self.pod = pod
        self.failed_predicates = failed_predicates
        super().__init__(
            f"pod {pod.meta.key} failed to fit on {len(failed_predicates)} node(s)"
        )


@dataclass
class ScheduleResult:
    node_name: str
    feasible_nodes: int
    evaluated_nodes: int
    scores: dict[str, int] = field(default_factory=dict)


class GenericScheduler:
    def __init__(
        self,
        predicates=None,
        priorities=None,
        extenders: Optional[list] = None,
    ):
        self.predicates = predicates if predicates is not None else dict(DEFAULT_PREDICATES)
        self.priorities = priorities if priorities is not None else default_priorities()
        self.extenders = extenders or []
        self._round_robin = 0  # selectHost tie-break counter (lastNodeIndex)

    # -- the three phases --------------------------------------------------
    def find_nodes_that_fit(
        self,
        pod: api.Pod,
        node_names: list[str],
        node_info_map: dict[str, NodeInfo],
        ctx: PredicateContext,
    ) -> tuple[list[str], dict[str, list[str]]]:
        """(``:163``) feasibility over the node axis.  The reference
        parallelizes with 16 workers (P1); the oracle stays sequential —
        the node axis is exactly what the TPU shards instead."""
        meta = compute_metadata(pod, ctx)
        if self.predicates == DEFAULT_PREDICATES:
            from ..models.snapshot import pod_signature_key

            # fused inline pass — identical feasibility, first-fail reasons
            # the sig key engages the per-NodeInfo equivalence cache
            feasible, failures = fast_fit_nodes(
                pod, meta, node_names, node_info_map, ctx,
                sig_key=pod_signature_key(pod),
            )
        else:
            feasible = []
            failures = {}
            for name in node_names:
                ok, reasons = pod_fits_on_node(
                    pod, meta, node_info_map[name], ctx, self.predicates
                )
                if ok:
                    feasible.append(name)
                else:
                    failures[name] = reasons
        for ext in self.extenders:
            if not feasible:
                break
            feasible, ext_failures = ext.filter(pod, feasible)
            failures.update(ext_failures)
        return feasible, failures

    def prioritize_nodes(
        self,
        pod: api.Pod,
        feasible: list[str],
        node_info_map: dict[str, NodeInfo],
        pctx: PriorityContext,
    ) -> list[tuple[str, int]]:
        """(``:285``) integer weighted sum of per-priority 0..10 scores."""
        infos = [node_info_map[n] for n in feasible]
        totals = [0] * len(feasible)
        for prio, weight in self.priorities:
            scores = prio.compute_all(pod, infos, pctx)
            for i, s in enumerate(scores):
                totals[i] += weight * s
        for ext in self.extenders:
            ext_scores = ext.prioritize(pod, feasible)
            for i, s in enumerate(ext_scores):
                totals[i] += s
        return list(zip(feasible, totals))

    def select_host(self, priority_list: list[tuple[str, int]]) -> str:
        """(``:144``) argmax with round-robin tie-break in node-axis order."""
        if not priority_list:
            raise ValueError("empty priority list")
        max_score = max(s for _, s in priority_list)
        ties = [n for n, s in priority_list if s == max_score]
        idx = self._round_robin % len(ties)
        self._round_robin += 1
        return ties[idx]

    # -- entry point -------------------------------------------------------
    def schedule(
        self,
        pod: api.Pod,
        node_info_map: dict[str, NodeInfo],
        pctx: Optional[PriorityContext] = None,
    ) -> ScheduleResult:
        node_names = sorted(n for n, i in node_info_map.items() if i.node is not None)
        if not node_names:
            raise FitError(pod, {})
        pctx = pctx or PriorityContext(node_info_map)
        ctx = PredicateContext(node_info_map, pvcs=pctx.pvcs, pvs=pctx.pvs,
                               services=pctx.services)
        feasible, failures = self.find_nodes_that_fit(pod, node_names, node_info_map, ctx)
        if not feasible:
            raise FitError(pod, failures)
        if len(feasible) == 1:
            return ScheduleResult(feasible[0], 1, len(node_names))
        prioritized = self.prioritize_nodes(pod, feasible, node_info_map, pctx)
        host = self.select_host(prioritized)
        return ScheduleResult(
            host, len(feasible), len(node_names), scores=dict(prioritized)
        )
