"""Device mesh / sharding utilities (the ICI-collective layer)."""

from .mesh import (
    NODE_AXIS,
    assert_collective_structure,
    loop_in_specs,
    loop_out_specs,
    make_mesh,
    match_partition_rules,
    mesh_dispatch_span,
    place_state,
    place_static,
    schedule_batch_sharded,
    schedule_batch_sharded_verified,
    shard_state,
    shard_static,
    sharded_hlo,
    state_specs,
    static_specs,
)
