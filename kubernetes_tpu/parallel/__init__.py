"""Device mesh / sharding utilities (the ICI-collective layer)."""

from .mesh import NODE_AXIS, make_mesh, schedule_batch_sharded, shard_state, shard_static
