"""Device mesh / sharding utilities (the ICI-collective layer)."""

from .mesh import (
    NODE_AXIS,
    assert_collective_structure,
    make_mesh,
    schedule_batch_sharded,
    schedule_batch_sharded_verified,
    shard_state,
    shard_static,
    sharded_hlo,
)
