"""Device mesh + sharding for the scheduling kernel.

The reference's node-axis parallel-for (16 workers,
``core/generic_scheduler.go:204``, SURVEY.md P1) is THE data-parallel axis
of a cluster scheduler.  Here it becomes a real mesh axis: every [N]-shaped
dynamic-state array and the N column of the [G, N] signature arrays shard
over ``nodes``; XLA GSPMD inserts the collectives (max/sum reductions for
score normalization → all-reduce over ICI, the cumsum tie-break → prefix
exchange) exactly where the scan step needs them.

Scale-out model: one scheduler process drives a mesh of chips; 5k nodes /
8 chips = 640 node rows per chip, each step's work is elementwise on the
shard plus O(log chips) collectives.  Host↔device traffic stays at the
batch boundary (group ids in, chosen indices out) — the DCN/REST analogue
of SURVEY.md §5.8.
"""

from __future__ import annotations

import re
from functools import lru_cache
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.snapshot import BatchStatic, InitialState
from ..ops.batch_kernel import (
    StaticArrays,
    ScanState,
    _STATIC_NODE_AXES,
    _STATE_NODE_AXES,
    _runner_for,
    batch_xs,
    state_to_device,
    to_device,
)
from ..utils import tracing

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D mesh over the node axis (the framework's parallel axis)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (NODE_AXIS,))  # device: sync — host-side Device handles (no array data); built once per device set, off the per-wave path


# -- partition rules over pytrees -------------------------------------------


def match_partition_rules(rules, names):
    """First-match-wins regex rules → PartitionSpec per leaf name (the
    classic partition-rule-over-pytree idiom): every ``name`` is matched
    against the rule patterns in order; unmatched names replicate
    (``P()``).  Keeping the mapping RULE-driven — instead of a hand-kept
    spec per field — means a new node-axis plane added to
    ``StaticArrays``/``ScanState`` only needs its entry in the kernel's
    ``_*_NODE_AXES`` tables, and the loop specs below pick it up."""
    out = {}
    for name in names:
        spec = P()
        for pat, s in rules:
            if re.fullmatch(pat, name):
                spec = s
                break
        out[name] = spec
    return out


def _node_axis_spec(ax: int) -> P:
    """P with ``nodes`` on dimension ``ax`` (leading dims replicated)."""
    return P(*([None] * ax + [NODE_AXIS]))


@lru_cache(maxsize=1)
def static_specs() -> StaticArrays:
    """PartitionSpec per ``StaticArrays`` field, derived from the
    kernel's node-axis table: node planes shard, signature/term tables
    replicate."""
    rules = tuple((re.escape(f), _node_axis_spec(ax))
                  for f, ax in _STATIC_NODE_AXES.items())
    return StaticArrays(**match_partition_rules(rules, StaticArrays._fields))


@lru_cache(maxsize=1)
def state_specs() -> ScanState:
    """PartitionSpec per ``ScanState`` field (``still_ok`` — handled
    explicitly by the kernel's compaction, not in the axis table — shards
    its trailing node axis like every other [.., N] plane; ``round_robin``
    and ``total_match`` replicate)."""
    rules = tuple((re.escape(f), _node_axis_spec(ax))
                  for f, ax in _STATE_NODE_AXES.items())
    rules += ((re.escape("still_ok"), _node_axis_spec(1)),)
    return ScanState(**match_partition_rules(rules, ScanState._fields))


def loop_in_specs():
    """shard_map in_specs for the wave loop ``run(dev, xs_full, state,
    chosen_buf, start_chunk, n_chunks, compact_thresh)``: node planes
    partitioned, the pod-axis xs (7-tuple) and every scalar replicated."""
    return (static_specs(), (P(),) * 7, state_specs(), P(), P(), P(), P())


def loop_out_specs():
    """shard_map out_specs for the loop's ``(state, chosen_buf, cursor,
    want_compact, alive, n_alive)``: the carry planes stay partitioned,
    the chosen buffer / control scalars are replicated (identical on
    every shard — products of psum'd values), and the per-shard alive
    slices concatenate back to the global [N] mask."""
    return (state_specs(), P(), P(), P(), P(NODE_AXIS), P())


def place_static(dev: StaticArrays, mesh: Mesh) -> StaticArrays:
    """Commit every ``StaticArrays`` leaf to ``mesh`` per its rule-derived
    spec (node axis partitioned, the rest replicated)."""
    return StaticArrays(*(
        jax.device_put(arr, NamedSharding(mesh, spec))
        for arr, spec in zip(dev, static_specs())))


def place_state(state: ScanState, mesh: Mesh) -> ScanState:
    """Commit every ``ScanState`` leaf to ``mesh`` (a ``still_ok`` of
    None — non-frontier callers — passes through untouched)."""
    return ScanState(*(
        arr if arr is None else jax.device_put(arr, NamedSharding(mesh, spec))
        for arr, spec in zip(state, state_specs())))


def mesh_dispatch_span(mesh: Mesh, width: int):
    """The ``mesh.dispatch`` trace span wrapping every sharded loop
    dispatch: shard count + mesh shape + current node width ride the
    span attrs, so the wave trace shows WHERE the node axis was split
    without a second trace format (TC503/TC504 gate this hot path)."""
    tr = tracing.current()
    if tr is None:
        return tracing.NULL_SPAN
    return tr.span("mesh.dispatch", cat="mesh", shards=int(mesh.size),
                   mesh_shape=str(tuple(int(s) for s in mesh.shape.values())),
                   width=int(width))


def shard_static(dev: StaticArrays, mesh: Mesh) -> StaticArrays:
    """Place static arrays: node-axis sharded, signature axis replicated
    (the per-term / per-signature tables are small).  Placement is
    rule-driven — see ``static_specs``."""
    return place_static(dev, mesh)


def shard_state(state: ScanState, mesh: Mesh) -> ScanState:
    """Place the carry: the [T, N] expanded domain counters shard on the
    node axis like every other per-node map (updates are elementwise
    same-domain masks — no cross-shard scatter); ``round_robin`` and
    ``total_match`` are the only replicated dynamic state."""
    return place_state(state, mesh)


def _prepare(static: BatchStatic, init: InitialState, mesh: Mesh):
    """Shared setup for every sharded entry point — one place to change
    placement/xs policy so the asserted HLO can never diverge from the
    executed program."""
    dev = shard_static(to_device(static), mesh)
    state = shard_state(
        state_to_device(init, r_sel=getattr(static, "r_sel", None)), mesh)
    xs = batch_xs(static)  # per-pod inputs replicate (scan slices [W] rows)
    return _runner_for(static), dev, xs, state


def schedule_batch_sharded(
    static: BatchStatic, init: InitialState, mesh: Mesh
) -> tuple[np.ndarray, int]:
    """Run the scan kernel with the node axis sharded over ``mesh``.

    The padded node count must divide evenly by the mesh size (the
    tensorizer's ``pad_multiple`` should be a multiple of it)."""
    run, dev, xs, state = _prepare(static, init, mesh)
    final_state, chosen = run(dev, xs, state)
    return np.asarray(chosen)[: len(static.group_of_pod)], int(final_state.round_robin)


def sharded_hlo(static: BatchStatic, init: InitialState, mesh: Mesh) -> str:
    """Optimized (post-GSPMD) HLO of the sharded scan — the collective
    structure the mesh layout implies.  Tests and the multichip dryrun
    assert over this text that no per-step all-gather of sharded
    [G, N] / [T, N] state crept in (SURVEY §2.13 P1 / §5.8: per-step
    traffic must be O(log chips) reductions, never a full node-axis
    re-materialization)."""
    run, dev, xs, state = _prepare(static, init, mesh)
    return run.lower(dev, xs, state).compile().as_text()


def schedule_batch_sharded_verified(
    static: BatchStatic, init: InitialState, mesh: Mesh
) -> tuple[np.ndarray, int, dict]:
    """Compile ONCE, assert the collective structure over the compiled
    text, then execute that same executable — the multichip dryrun path
    (avoids paying the scan's XLA compile twice per workload)."""
    run, dev, xs, state = _prepare(static, init, mesh)
    compiled = run.lower(dev, xs, state).compile()
    counts = assert_collective_structure(compiled.as_text(), static)
    final_state, chosen = compiled(dev, xs, state)
    return (np.asarray(chosen)[: len(static.group_of_pod)],
            int(final_state.round_robin), counts)


def assert_collective_structure(hlo: str, static: BatchStatic) -> dict:
    """Fail if the sharded program all-gathers node-axis state.

    Allowed collectives: all-reduce / reduce-scatter / collective-permute
    of any size (score normalization, cumsum tie-break) and SMALL
    all-gathers (boundary exchanges, scalars).  Forbidden: an all-gather
    whose result is on the order of a full [G, N] or [T, N] array — the
    signature of a sharding regression that re-materializes the sharded
    state on every step.  Returns collective counts for reporting."""
    import re

    n_pad = int(static.n_pad)
    g = int(static.static_ok.shape[0])
    t = int(static.term_matches_sig.shape[0])
    # threshold: half a [G,N] (or [T,N]) plane — generous room for
    # legitimate small gathers, far below full-state re-materialization
    limit = max(g, t, 2) * n_pad // 2
    counts = {"all-gather": 0, "all-reduce": 0, "reduce-scatter": 0,
              "collective-permute": 0}
    offending = []
    for line in hlo.splitlines():
        for op in counts:
            if f" {op}(" in line or f"{op}-start(" in line:
                counts[op] += 1
                if op == "all-gather":
                    # async pairs report tuple results whose FIRST shape
                    # is the pre-gather shard — take the LARGEST shape on
                    # the line so the full gathered plane can't hide in a
                    # (shard, full) tuple on a wide mesh
                    elems = 1
                    for dims in re.findall(r"\[([\d,]+)\]", line):
                        cur = 1
                        for d in dims.split(","):
                            cur *= int(d)
                        elems = max(elems, cur)
                    if elems >= limit:
                        offending.append(line.strip()[:200])
    assert not offending, (
        f"sharded scan all-gathers node-axis state (>{limit} elems): "
        + "; ".join(offending[:3]))
    return counts
