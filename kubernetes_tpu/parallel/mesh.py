"""Device mesh + sharding for the scheduling kernel.

The reference's node-axis parallel-for (16 workers,
``core/generic_scheduler.go:204``, SURVEY.md P1) is THE data-parallel axis
of a cluster scheduler.  Here it becomes a real mesh axis: every [N]-shaped
dynamic-state array and the N column of the [G, N] signature arrays shard
over ``nodes``; XLA GSPMD inserts the collectives (max/sum reductions for
score normalization → all-reduce over ICI, the cumsum tie-break → prefix
exchange) exactly where the scan step needs them.

Scale-out model: one scheduler process drives a mesh of chips; 5k nodes /
8 chips = 640 node rows per chip, each step's work is elementwise on the
shard plus O(log chips) collectives.  Host↔device traffic stays at the
batch boundary (group ids in, chosen indices out) — the DCN/REST analogue
of SURVEY.md §5.8.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.snapshot import BatchStatic, InitialState
from ..ops.batch_kernel import (
    StaticArrays,
    ScanState,
    _runner_for,
    batch_xs,
    state_to_device,
    to_device,
)

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D mesh over the node axis (the framework's parallel axis)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (NODE_AXIS,))


def shard_static(dev: StaticArrays, mesh: Mesh) -> StaticArrays:
    """Place static arrays: node-axis sharded, signature axis replicated."""
    n = NamedSharding(mesh, P(NODE_AXIS))
    n_r = NamedSharding(mesh, P(NODE_AXIS, None))
    g_n = NamedSharding(mesh, P(None, NODE_AXIS))
    repl = NamedSharding(mesh, P())
    return StaticArrays(
        node_exists=jax.device_put(dev.node_exists, n),
        node_alloc=jax.device_put(dev.node_alloc, n_r),
        node_alloc_pods=jax.device_put(dev.node_alloc_pods, n),
        node_zone=jax.device_put(dev.node_zone, n),
        static_ok=jax.device_put(dev.static_ok, g_n),
        node_aff_raw=jax.device_put(dev.node_aff_raw, g_n),
        taint_intol_raw=jax.device_put(dev.taint_intol_raw, g_n),
        static_score=jax.device_put(dev.static_score, g_n),
        interpod_raw=jax.device_put(dev.interpod_raw, g_n),
        g_request=jax.device_put(dev.g_request, repl),
        g_nonzero=jax.device_put(dev.g_nonzero, repl),
        g_ports=jax.device_put(dev.g_ports, repl),
        g_has_spread=jax.device_put(dev.g_has_spread, repl),
        spread_inc=jax.device_put(dev.spread_inc, repl),
        # phase B: the [.., N] maps shard with the node axis; the per-term /
        # per-signature tables replicate (small)
        term_matches_sig=jax.device_put(dev.term_matches_sig, repl),
        sym_w=jax.device_put(dev.sym_w, repl),
        own_w=jax.device_put(dev.own_w, repl),
        own_ra=jax.device_put(dev.own_ra, repl),
        own_raa=jax.device_put(dev.own_raa, repl),
        own_all=jax.device_put(dev.own_all, repl),
        is_raa=jax.device_put(dev.is_raa, repl),
        self_match=jax.device_put(dev.self_match, repl),
        node_domain=jax.device_put(dev.node_domain, g_n),
        dom_valid=jax.device_put(dev.dom_valid, g_n),
        vol_limits=jax.device_put(dev.vol_limits, repl),
    )


def shard_state(state: ScanState, mesh: Mesh) -> ScanState:
    n = NamedSharding(mesh, P(NODE_AXIS))
    n_r = NamedSharding(mesh, P(NODE_AXIS, None))
    g_n = NamedSharding(mesh, P(None, NODE_AXIS))
    repl = NamedSharding(mesh, P())
    return ScanState(
        requested=jax.device_put(state.requested, n_r),
        nonzero_requested=jax.device_put(state.nonzero_requested, n_r),
        pod_count=jax.device_put(state.pod_count, n),
        ports_used=jax.device_put(state.ports_used, n_r),
        spread_counts=jax.device_put(state.spread_counts, g_n),
        round_robin=jax.device_put(state.round_robin, repl),
        # phase B: the [T, N] expanded domain counters shard on the node
        # axis like every other per-node map (updates are elementwise
        # same-domain masks — no cross-shard scatter); total_match is the
        # only replicated affinity state
        dm=jax.device_put(state.dm, g_n),
        downer=jax.device_put(state.downer, g_n),
        total_match=jax.device_put(state.total_match, repl),
        vol_any=jax.device_put(state.vol_any, g_n),
        vol_ns=jax.device_put(state.vol_ns, g_n),
        nk=jax.device_put(state.nk, g_n),
    )


def _prepare(static: BatchStatic, init: InitialState, mesh: Mesh):
    """Shared setup for every sharded entry point — one place to change
    placement/xs policy so the asserted HLO can never diverge from the
    executed program."""
    dev = shard_static(to_device(static), mesh)
    state = shard_state(
        state_to_device(init, r_sel=getattr(static, "r_sel", None)), mesh)
    xs = batch_xs(static)  # per-pod inputs replicate (scan slices [W] rows)
    return _runner_for(static), dev, xs, state


def schedule_batch_sharded(
    static: BatchStatic, init: InitialState, mesh: Mesh
) -> tuple[np.ndarray, int]:
    """Run the scan kernel with the node axis sharded over ``mesh``.

    The padded node count must divide evenly by the mesh size (the
    tensorizer's ``pad_multiple`` should be a multiple of it)."""
    run, dev, xs, state = _prepare(static, init, mesh)
    final_state, chosen = run(dev, xs, state)
    return np.asarray(chosen)[: len(static.group_of_pod)], int(final_state.round_robin)


def sharded_hlo(static: BatchStatic, init: InitialState, mesh: Mesh) -> str:
    """Optimized (post-GSPMD) HLO of the sharded scan — the collective
    structure the mesh layout implies.  Tests and the multichip dryrun
    assert over this text that no per-step all-gather of sharded
    [G, N] / [T, N] state crept in (SURVEY §2.13 P1 / §5.8: per-step
    traffic must be O(log chips) reductions, never a full node-axis
    re-materialization)."""
    run, dev, xs, state = _prepare(static, init, mesh)
    return run.lower(dev, xs, state).compile().as_text()


def schedule_batch_sharded_verified(
    static: BatchStatic, init: InitialState, mesh: Mesh
) -> tuple[np.ndarray, int, dict]:
    """Compile ONCE, assert the collective structure over the compiled
    text, then execute that same executable — the multichip dryrun path
    (avoids paying the scan's XLA compile twice per workload)."""
    run, dev, xs, state = _prepare(static, init, mesh)
    compiled = run.lower(dev, xs, state).compile()
    counts = assert_collective_structure(compiled.as_text(), static)
    final_state, chosen = compiled(dev, xs, state)
    return (np.asarray(chosen)[: len(static.group_of_pod)],
            int(final_state.round_robin), counts)


def assert_collective_structure(hlo: str, static: BatchStatic) -> dict:
    """Fail if the sharded program all-gathers node-axis state.

    Allowed collectives: all-reduce / reduce-scatter / collective-permute
    of any size (score normalization, cumsum tie-break) and SMALL
    all-gathers (boundary exchanges, scalars).  Forbidden: an all-gather
    whose result is on the order of a full [G, N] or [T, N] array — the
    signature of a sharding regression that re-materializes the sharded
    state on every step.  Returns collective counts for reporting."""
    import re

    n_pad = int(static.n_pad)
    g = int(static.static_ok.shape[0])
    t = int(static.term_matches_sig.shape[0])
    # threshold: half a [G,N] (or [T,N]) plane — generous room for
    # legitimate small gathers, far below full-state re-materialization
    limit = max(g, t, 2) * n_pad // 2
    counts = {"all-gather": 0, "all-reduce": 0, "reduce-scatter": 0,
              "collective-permute": 0}
    offending = []
    for line in hlo.splitlines():
        for op in counts:
            if f" {op}(" in line or f"{op}-start(" in line:
                counts[op] += 1
                if op == "all-gather":
                    # async pairs report tuple results whose FIRST shape
                    # is the pre-gather shard — take the LARGEST shape on
                    # the line so the full gathered plane can't hide in a
                    # (shard, full) tuple on a wide mesh
                    elems = 1
                    for dims in re.findall(r"\[([\d,]+)\]", line):
                        cur = 1
                        for d in dims.split(","):
                            cur *= int(d)
                        elems = max(elems, cur)
                    if elems >= limit:
                        offending.append(line.strip()[:200])
    assert not offending, (
        f"sharded scan all-gathers node-axis state (>{limit} elems): "
        + "; ".join(offending[:3]))
    return counts
