"""Admission framework: mutating+validating plugin chain on the write path.

Capability equivalent of the reference's admission machinery
(``staging/src/k8s.io/apiserver/pkg/admission`` — ``Interface``/
``MutationInterface``/``ValidationInterface`` and the chain in
``chain.go``), wired the way the reference wires it: inside the write
handlers *before* storage (``endpoints/handlers/rest.go:388`` runs
``admit.Admit`` then ``Validate`` before ``registry.Store.Create``).

Here the seam is ``AdmittedStore`` — a ``Store`` subclass whose
create/update/delete run the chain first.  Both the in-proc ``Clientset``
and the wire ``APIServer`` take any Store, so admission slots under either
without touching callers.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

from ..store.store import Store

CREATE = "CREATE"
UPDATE = "UPDATE"
DELETE = "DELETE"


class AdmissionDenied(Exception):
    """Request rejected by a plugin (HTTP 403 Forbidden analogue)."""

    def __init__(self, plugin: str, message: str):
        super().__init__(f"admission denied by {plugin}: {message}")
        self.plugin = plugin
        self.message = message


@dataclass
class Attributes:
    """What a plugin may inspect (reference ``admission.Attributes``).

    ``obj`` is the incoming wire dict (mutable during the mutate phase);
    ``old_obj`` is the stored object on UPDATE/DELETE.  ``store`` gives
    plugins read access to cluster state (the reference hands plugins
    informers; one in-proc store plays that role here).  ``user`` is the
    authenticated username (empty until the auth stack fills it)."""

    operation: str
    kind: str
    namespace: str
    name: str
    obj: Optional[dict] = None
    old_obj: Optional[dict] = None
    store: Optional[Store] = None
    user: str = ""
    extras: dict = field(default_factory=dict)


class AdmissionPlugin:
    """Base plugin; override ``admit`` (mutate) and/or ``validate``."""

    name = "Plugin"
    # which operations the plugin cares about (reference Handles())
    operations = (CREATE, UPDATE)

    def handles(self, attrs: Attributes) -> bool:
        return attrs.operation in self.operations

    def admit(self, attrs: Attributes) -> None:  # mutate phase
        pass

    def validate(self, attrs: Attributes) -> None:  # validate phase
        pass

    def deny(self, message: str):
        raise AdmissionDenied(self.name, message)


class AdmissionChain:
    """Runs every plugin's mutate pass, then every plugin's validate pass
    (reference ``chainAdmissionHandler`` — mutators before validators)."""

    def __init__(self, plugins: list[AdmissionPlugin]):
        self.plugins = list(plugins)
        # Reentrancy guard: writes a plugin itself issues against the store
        # (e.g. the quota plugin's CAS on ResourceQuota.status) must not
        # re-enter the chain.
        self._local = threading.local()

    def run(self, attrs: Attributes) -> None:
        if getattr(self._local, "depth", 0) > 0:
            return
        self._local.depth = 1
        try:
            for p in self.plugins:
                if p.handles(attrs):
                    p.admit(attrs)
            for p in self.plugins:
                if p.handles(attrs):
                    p.validate(attrs)
        finally:
            self._local.depth = 0


class AdmittedStore(Store):
    """Store with an admission chain on the write path.

    ``guaranteed_update`` and typed-client writes route through ``update``,
    so every mutation passes the chain; binds (``bind_many``) are the
    scheduler's commit path and bypass admission exactly as the reference's
    BindingREST does (no admission on subresources in this era)."""

    def __init__(self, chain: Optional[AdmissionChain] = None, **kwargs):
        super().__init__(**kwargs)
        self.chain = chain or AdmissionChain([])
        # per-request identity, set by the apiserver's auth filter; thread-
        # local because ThreadingHTTPServer handles requests concurrently
        self._user_local = threading.local()

    @property
    def user(self) -> str:
        return getattr(self._user_local, "name", "")

    @user.setter
    def user(self, name: str) -> None:
        self._user_local.name = name

    def _attrs(self, op: str, kind: str, obj: Optional[dict], old: Optional[dict],
               namespace: str, name: str) -> Attributes:
        return Attributes(
            operation=op, kind=kind, namespace=namespace, name=name,
            obj=obj, old_obj=old, store=self, user=self.user,
        )

    def create(self, kind: str, obj: dict) -> dict:
        meta = obj.get("metadata") or {}
        self.chain.run(self._attrs(
            CREATE, kind, obj, None,
            meta.get("namespace", "default"), meta.get("name", ""),
        ))
        return super().create(kind, obj)

    def update(self, kind: str, obj: dict, expect_rev=None, _trusted: bool = False) -> dict:
        meta = obj.get("metadata") or {}
        namespace = meta.get("namespace", "default")
        name = meta.get("name", "")
        try:
            old = super().get(kind, namespace, name)
        except KeyError:
            old = None
        self.chain.run(self._attrs(UPDATE, kind, obj, old, namespace, name))
        return super().update(kind, obj, expect_rev=expect_rev, _trusted=_trusted)

    def delete(self, kind: str, namespace: str, name: str, expect_rev=None) -> dict:
        try:
            old = super().get(kind, namespace, name)
        except KeyError:
            old = None
        self.chain.run(self._attrs(DELETE, kind, None, old, namespace, name))
        return super().delete(kind, namespace, name, expect_rev=expect_rev)
