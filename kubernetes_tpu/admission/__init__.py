"""Admission control (SURVEY.md §2.3 — apiserver/pkg/admission +
plugin/pkg/admission/*): mutating/validating plugin chain on the write
path, plus the quota evaluator library (pkg/quota)."""

from .framework import (
    CREATE,
    DELETE,
    UPDATE,
    AdmissionChain,
    AdmissionDenied,
    AdmissionPlugin,
    AdmittedStore,
    Attributes,
)
from .plugins import (
    IMMORTAL_NAMESPACES,
    DefaultTolerationSeconds,
    LimitPodHardAntiAffinityTopology,
    LimitRanger,
    NamespaceLifecycle,
    Priority,
    ResourceQuota,
    ServiceAccount,
    default_chain,
)
from .plugins_ext import (
    AlwaysAdmit,
    AlwaysDeny,
    DenyEscalatingExec,
    Initializers,
    NamespaceAutoProvision,
    OwnerReferencesPermissionEnforcement,
    PersistentVolumeLabel,
    SecurityContextDeny,
    AlwaysPullImages,
    DefaultStorageClass,
    GenericAdmissionWebhook,
    ImagePolicyWebhook,
    NodeRestriction,
    PodNodeSelector,
    PodPreset,
    PodSecurityPolicyPlugin,
    ServiceIPAllocator,
)
from . import quota
