"""Extended admission plugins (toward the reference's full default set).

Capability equivalents of ``plugin/pkg/admission/*``:

- DefaultStorageClass        — ``storageclass/default/admission.go``
- PodPreset                  — ``podpreset/admission.go``
- AlwaysPullImages           — ``alwayspullimages/admission.go``
- PodNodeSelector            — ``podnodeselector/admission.go``
- ImagePolicyWebhook         — ``imagepolicy/admission.go``
- GenericAdmissionWebhook    — ``webhook/admission.go`` (external
  validating webhooks with a failure policy)
- NodeRestriction            — ``noderestriction/admission.go``

Webhook transports are injectable callables (tests pass functions; the
HTTP form posts JSON like the scheduler extender does), because the
webhook CONTRACT — review request in, allow/deny out, failure policy on
error — is the capability, not the socket."""

from __future__ import annotations

import json
import urllib.request
from typing import Callable, Optional

from ..api.selectors import LabelSelector
from ..store.store import NotFoundError
from .framework import CREATE, DELETE, UPDATE, AdmissionPlugin, Attributes


class DefaultStorageClass(AdmissionPlugin):
    """PVCs created without a class get the cluster default
    (``storageclass/default/admission.go``: exactly one class annotated
    default; ambiguous defaults deny)."""

    name = "DefaultStorageClass"
    operations = (CREATE,)

    def handles(self, attrs: Attributes) -> bool:
        return attrs.kind == "PersistentVolumeClaim" and super().handles(attrs)

    def admit(self, attrs: Attributes) -> None:
        spec = attrs.obj.setdefault("spec", {})
        if spec.get("storageClassName"):
            return
        defaults = [
            d for d in attrs.store.list("StorageClass", None)[0] if d.get("isDefault")
        ]
        if not defaults:
            return
        if len(defaults) > 1:
            self.deny("more than one default StorageClass")
        spec["storageClassName"] = defaults[0]["metadata"]["name"]


class PodPreset(AdmissionPlugin):
    """Inject env/volumes from matching PodPresets into pods at create
    (``podpreset/admission.go``); a merge CONFLICT (the pod already sets a
    key the preset would set, with a different value) skips the entire
    preset — no partial application."""

    name = "PodPreset"
    operations = (CREATE,)

    def handles(self, attrs: Attributes) -> bool:
        return attrs.kind == "Pod" and super().handles(attrs)

    def admit(self, attrs: Attributes) -> None:
        labels = (attrs.obj.get("metadata") or {}).get("labels") or {}
        spec = attrs.obj.setdefault("spec", {})
        applied = []
        for raw in attrs.store.list("PodPreset", attrs.namespace)[0]:
            preset_spec = raw.get("spec") or {}
            sel = LabelSelector.from_dict(preset_spec.get("selector"))
            if not sel.matches(labels):
                continue
            env = preset_spec.get("env") or {}
            conflict = any(
                k in (c.get("env") or {}) and c["env"][k] != v
                for c in spec.get("containers") or []
                for k, v in env.items()
            ) or any(
                v.get("name") == pv.get("name") and v != pv
                for v in spec.get("volumes") or []
                for pv in preset_spec.get("volumes") or []
            )
            if conflict:
                continue  # the whole preset is skipped, nothing applied
            for c in spec.setdefault("containers", []):
                merged = dict(env)
                merged.update(c.get("env") or {})
                if merged:
                    c["env"] = merged
            have = {v.get("name") for v in spec.get("volumes") or []}
            for vol in preset_spec.get("volumes") or []:
                if vol.get("name") not in have:
                    spec.setdefault("volumes", []).append(dict(vol))
            applied.append(raw["metadata"]["name"])
        if applied:
            meta = attrs.obj.setdefault("metadata", {})
            anns = meta.setdefault("annotations", {})
            for name in applied:
                anns[f"podpreset.admission.kubernetes.io/podpreset-{name}"] = "applied"


class AlwaysPullImages(AdmissionPlugin):
    """Force imagePullPolicy=Always (``alwayspullimages/admission.go``:
    multi-tenant nodes must not serve cached private images)."""

    name = "AlwaysPullImages"
    operations = (CREATE, UPDATE)

    def handles(self, attrs: Attributes) -> bool:
        return attrs.kind == "Pod" and super().handles(attrs)

    def admit(self, attrs: Attributes) -> None:
        for c in (attrs.obj.get("spec") or {}).get("containers") or []:
            c["imagePullPolicy"] = "Always"

    def validate(self, attrs: Attributes) -> None:
        for c in (attrs.obj.get("spec") or {}).get("containers") or []:
            if c.get("imagePullPolicy") != "Always":
                self.deny(f"container {c.get('name')} must pull Always")


class PodNodeSelector(AdmissionPlugin):
    """Merge the namespace's node-selector annotation into pods; a pod
    selector conflicting with the namespace's is denied
    (``podnodeselector/admission.go``)."""

    name = "PodNodeSelector"
    operations = (CREATE,)
    ANNOTATION = "scheduler.alpha.kubernetes.io/node-selector"

    def handles(self, attrs: Attributes) -> bool:
        return attrs.kind == "Pod" and super().handles(attrs)

    def _namespace_selector(self, attrs: Attributes) -> dict:
        try:
            ns = attrs.store.get("Namespace", "", attrs.namespace)
        except NotFoundError:
            return {}
        raw = ((ns.get("metadata") or {}).get("annotations") or {}).get(self.ANNOTATION, "")
        out = {}
        for part in raw.split(","):
            part = part.strip()
            if part and "=" in part:
                k, v = part.split("=", 1)
                out[k.strip()] = v.strip()
        return out

    def admit(self, attrs: Attributes) -> None:
        want = self._namespace_selector(attrs)
        if not want:
            return
        spec = attrs.obj.setdefault("spec", {})
        sel = spec.setdefault("nodeSelector", {})
        for k, v in want.items():
            if k in sel and sel[k] != v:
                self.deny(f"pod node selector {k}={sel[k]} conflicts with namespace {k}={v}")
            sel[k] = v


class ImagePolicyWebhook(AdmissionPlugin):
    """Ask an external image-policy service whether the pod's images are
    allowed (``imagepolicy/admission.go``).  ``default_allow`` is the
    failure policy when the backend is unreachable."""

    name = "ImagePolicyWebhook"
    operations = (CREATE,)

    def __init__(self, backend: Optional[Callable[[dict], dict]] = None,
                 url: Optional[str] = None, default_allow: bool = False,
                 timeout: float = 5.0):
        if backend is None and url is None:
            # surface misconfiguration at wiring time, not as a perpetual
            # "backend unreachable" that the failure policy silently eats
            raise ValueError("ImagePolicyWebhook needs a backend or a url")
        self.backend = backend
        self.url = url
        self.default_allow = default_allow
        self.timeout = timeout

    def handles(self, attrs: Attributes) -> bool:
        return attrs.kind == "Pod" and super().handles(attrs)

    def _review(self, payload: dict) -> dict:
        if self.backend is not None:
            return self.backend(payload)
        req = urllib.request.Request(
            self.url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def validate(self, attrs: Attributes) -> None:
        images = [c.get("image", "") for c in
                  (attrs.obj.get("spec") or {}).get("containers") or []]
        payload = {"spec": {"containers": [{"image": i} for i in images],
                            "namespace": attrs.namespace}}
        try:
            result = self._review(payload)
        except Exception:
            if self.default_allow:
                return
            self.deny("image policy backend unreachable (failure policy: deny)")
        if not (result.get("status") or {}).get("allowed", False):
            reason = (result.get("status") or {}).get("reason", "image rejected")
            self.deny(reason)


class GenericAdmissionWebhook(AdmissionPlugin):
    """External validating webhooks (``webhook/admission.go``): each rule
    names the kinds it reviews; ``fail_open`` webhooks admit on backend
    error, fail-closed ones deny."""

    name = "GenericAdmissionWebhook"
    operations = (CREATE, UPDATE, DELETE)

    def __init__(self, webhooks: Optional[list[dict]] = None, timeout: float = 5.0):
        # each: {name, kinds: [..] | ["*"], backend: callable | url: str,
        #        fail_open: bool}
        self.webhooks = webhooks or []
        self.timeout = timeout

    def _call(self, hook: dict, payload: dict) -> dict:
        backend = hook.get("backend")
        if backend is not None:
            return backend(payload)
        req = urllib.request.Request(
            hook["url"], data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def validate(self, attrs: Attributes) -> None:
        payload = {
            "request": {
                "operation": attrs.operation,
                "kind": attrs.kind,
                "namespace": attrs.namespace,
                "name": attrs.name,
                "object": attrs.obj,
                "oldObject": attrs.old_obj,
                "userInfo": {"username": attrs.user},
            }
        }
        for hook in self.webhooks:
            kinds = hook.get("kinds", ["*"])
            if "*" not in kinds and attrs.kind not in kinds:
                continue
            try:
                result = self._call(hook, payload)
            except Exception:
                if hook.get("fail_open", False):
                    continue
                self.deny(f"webhook {hook.get('name')} unreachable (fail closed)")
            response = result.get("response") or {}
            if not response.get("allowed", False):
                msg = (response.get("status") or {}).get("message", "denied")
                self.deny(f"webhook {hook.get('name')}: {msg}")


class ServiceIPAllocator(AdmissionPlugin):
    """ClusterIP + NodePort allocation at service create (the capability
    of the reference's service REST registry allocators,
    ``pkg/registry/core/service`` — placed on the write path the way all
    of this framework's registry behavior is)."""

    name = "ServiceIPAllocator"
    operations = (CREATE,)

    def __init__(self, service_cidr: str = "10.0.0.0/16",
                 node_port_range: tuple[int, int] = (30000, 32767)):
        import ipaddress

        self.network = ipaddress.ip_network(service_cidr)
        self.node_port_range = node_port_range

    def handles(self, attrs: Attributes) -> bool:
        return attrs.kind == "Service" and super().handles(attrs)

    def admit(self, attrs: Attributes) -> None:
        import ipaddress

        spec = attrs.obj.setdefault("spec", {})
        existing, _ = attrs.store.list("Service", None)
        used_ips = {s.get("spec", {}).get("clusterIP", "") for s in existing}
        used_ports = {
            p.get("nodePort", 0)
            for s in existing
            for p in s.get("spec", {}).get("ports", [])
        }
        ip = spec.get("clusterIP", "")
        if ip == "":
            for candidate in self.network.hosts():
                c = str(candidate)
                if c not in used_ips:
                    spec["clusterIP"] = c
                    break
            else:
                self.deny("service CIDR exhausted")
        elif ip != "None":
            try:
                addr = ipaddress.ip_address(ip)
            except ValueError:
                self.deny(f"invalid clusterIP {ip!r}")
            if addr not in self.network:
                self.deny(f"clusterIP {ip} not in service CIDR {self.network}")
            if ip in used_ips:
                self.deny(f"clusterIP {ip} already allocated")
        if spec.get("type") in ("NodePort", "LoadBalancer"):
            lo, hi = self.node_port_range
            for port in spec.get("ports", []):
                np = int(port.get("nodePort", 0) or 0)
                if np == 0:
                    for candidate in range(lo, hi + 1):
                        if candidate not in used_ports:
                            port["nodePort"] = candidate
                            used_ports.add(candidate)
                            break
                    else:
                        self.deny("node port range exhausted")
                elif np in used_ports:
                    self.deny(f"node port {np} already allocated")
                elif not (lo <= np <= hi):
                    self.deny(f"node port {np} outside range {lo}-{hi}")
                else:
                    used_ports.add(np)


class NodeRestriction(AdmissionPlugin):
    """Kubelets (``system:node:<name>``) may only modify their own Node
    object and pods bound to them (``noderestriction/admission.go``)."""

    name = "NodeRestriction"
    operations = (CREATE, UPDATE, DELETE)
    PREFIX = "system:node:"

    def validate(self, attrs: Attributes) -> None:
        if not attrs.user.startswith(self.PREFIX):
            return
        node_name = attrs.user[len(self.PREFIX):]
        if attrs.kind == "Node":
            if attrs.name != node_name:
                self.deny(f"node {node_name} may not modify node {attrs.name}")
            return
        if attrs.kind == "Pod":
            ref = attrs.obj if attrs.operation != DELETE else attrs.old_obj
            bound = ((ref or {}).get("spec") or {}).get("nodeName", "")
            if bound != node_name:
                self.deny(f"node {node_name} may only manage its own pods")
            return
        self.deny(f"node {node_name} may not write {attrs.kind} objects")


class NamespaceAutoProvision(AdmissionPlugin):
    """Create the namespace on first use instead of rejecting
    (``autoprovision/admission.go`` — the permissive sibling of
    NamespaceLifecycle's exists-check)."""

    name = "NamespaceAutoProvision"
    operations = (CREATE,)

    def admit(self, attrs: Attributes) -> None:
        if not attrs.namespace or attrs.kind == "Namespace":
            return
        try:
            attrs.store.get("Namespace", "", attrs.namespace)
        except NotFoundError:
            from ..api.cluster import Namespace
            from ..api.meta import ObjectMeta
            from ..store.store import AlreadyExistsError

            try:
                attrs.store.create(
                    "Namespace",
                    Namespace(meta=ObjectMeta(name=attrs.namespace)).to_dict(),
                )
            except AlreadyExistsError:
                pass  # racing creates are fine; anything else surfaces


class SecurityContextDeny(AdmissionPlugin):
    """Reject privileged containers (``securitycontextdeny/admission.go``
    at the depth this pod model carries security context)."""

    name = "SecurityContextDeny"
    operations = (CREATE, UPDATE)

    def handles(self, attrs: Attributes) -> bool:
        return attrs.kind == "Pod" and super().handles(attrs)

    def validate(self, attrs: Attributes) -> None:
        for c in (attrs.obj.get("spec") or {}).get("containers") or []:
            if (c.get("securityContext") or {}).get("privileged"):
                self.deny(f"container {c.get('name')} requests privileged mode")


class AlwaysAdmit(AdmissionPlugin):
    """``admit/admission.go`` — the no-op plugin (testing/default glue)."""

    name = "AlwaysAdmit"
    operations = (CREATE, UPDATE, DELETE)


class AlwaysDeny(AdmissionPlugin):
    """``deny/admission.go`` — rejects everything (lockdown/testing)."""

    name = "AlwaysDeny"
    operations = (CREATE, UPDATE, DELETE)

    def validate(self, attrs: Attributes) -> None:
        self.deny("AlwaysDeny rejects all requests")


class DenyEscalatingExec(AdmissionPlugin):
    """Reject exec/attach on privileged pods
    (``plugin/pkg/admission/exec/admission.go`` DenyEscalatingExec):
    create-exec rights must not escalate into the host through a
    privileged or host-namespace container."""

    name = "DenyEscalatingExec"
    operations = ("CONNECT",)

    def handles(self, attrs: Attributes) -> bool:
        return attrs.kind == "Pod" and attrs.operation == "CONNECT"

    def validate(self, attrs: Attributes) -> None:
        pod = attrs.old_obj or {}
        spec = pod.get("spec") or {}
        for flag in ("hostPID", "hostIPC", "hostNetwork"):
            if spec.get(flag):
                self.deny(f"cannot exec into a pod sharing the host's "
                          f"{flag[4:].lower()} namespace")
        for c in (spec.get("containers") or []) + (spec.get("initContainers") or []):
            if (c.get("securityContext") or {}).get("privileged"):
                self.deny(
                    f"cannot exec into privileged container {c.get('name')!r}")


class OwnerReferencesPermissionEnforcement(AdmissionPlugin):
    """``plugin/pkg/admission/gc/gc_admission.go``: changing an object's
    ownerReferences requires DELETE rights on the object — otherwise a
    user with only update rights could trick the garbage collector into
    deleting objects for them (set an ownerRef to something they can
    delete, remove the owner, GC does the rest)."""

    name = "OwnerReferencesPermissionEnforcement"
    operations = (UPDATE,)

    def __init__(self, authorizer=None):
        # authorizer is optional: without one, ownerRef changes by
        # non-privileged identities are denied outright (fail closed)
        self.authorizer = authorizer

    def validate(self, attrs: Attributes) -> None:
        new_refs = ((attrs.obj or {}).get("metadata") or {}).get("ownerReferences") or []
        old_refs = ((attrs.old_obj or {}).get("metadata") or {}).get("ownerReferences") or []
        if new_refs == old_refs:
            return
        user = attrs.user or ""
        if user.startswith("system:") or not user:
            # controllers (and the unauthenticated in-proc path) manage
            # ownership legitimately — the reference exempts them via RBAC
            return
        if self.authorizer is not None:
            from ..auth import ALLOW, AuthzAttributes, UserInfo
            from ..api.types import KIND_PLURALS

            decision, _ = self.authorizer.authorize(AuthzAttributes(
                user=UserInfo(name=user), verb="delete",
                resource=KIND_PLURALS.get(attrs.kind, attrs.kind.lower()),
                namespace=attrs.namespace, name=attrs.name))
            if decision == ALLOW:
                return
        self.deny("cannot set/change ownerReferences without delete "
                  "permission on the object")


class PersistentVolumeLabel(AdmissionPlugin):
    """``plugin/pkg/admission/persistentvolume/label``: stamp cloud
    topology labels (zone/region) onto PersistentVolumes at create time
    so the volume-zone predicate can act on them."""

    name = "PersistentVolumeLabel"
    operations = (CREATE,)

    ZONE = "failure-domain.beta.kubernetes.io/zone"
    REGION = "failure-domain.beta.kubernetes.io/region"

    def __init__(self, cloud=None):
        self.cloud = cloud  # CloudProvider with zones(); None = inert

    def handles(self, attrs: Attributes) -> bool:
        return attrs.kind == "PersistentVolume" and super().handles(attrs)

    def admit(self, attrs: Attributes) -> None:
        if self.cloud is None or self.cloud.zones() is None:
            return
        meta = attrs.obj.setdefault("metadata", {})
        labels = meta.setdefault("labels", {})
        if self.ZONE in labels:
            return
        # the volume's disk lives where its (cloud) source does; the fake
        # cloud keys zone by the spec's source instance/disk name
        source = ((attrs.obj.get("spec") or {}).get("diskID")
                  or meta.get("name", ""))
        try:
            zone, region = self.cloud.zones().get_zone(source)
        except KeyError:
            return
        if zone:
            labels[self.ZONE] = zone
        if region:
            labels[self.REGION] = region


class Initializers(AdmissionPlugin):
    """``plugin/pkg/admission/initialization`` (alpha in the reference
    era): objects created with ``metadata.initializers.pending`` are
    hidden from ordinary LISTs until every initializer controller removes
    its entry; this plugin enforces the protocol — only the FIRST pending
    initializer may be removed per update, and new objects may not
    self-declare an empty-but-present result."""

    name = "Initializers"
    operations = (CREATE, UPDATE)

    def validate(self, attrs: Attributes) -> None:
        if attrs.operation == CREATE:
            init = ((attrs.obj or {}).get("metadata") or {}).get("initializers")
            if init is not None and "result" in init:
                # a creator may arrive WITH pending initializers (the
                # reference's initializer admission stamps them) but must
                # not self-declare completion
                self.deny("cannot create an object with a self-declared "
                          "initializer result")
            return
        new_pending = [i.get("name") for i in
                       (((attrs.obj or {}).get("metadata") or {})
                        .get("initializers") or {}).get("pending") or []]
        old_pending = [i.get("name") for i in
                       (((attrs.old_obj or {}).get("metadata") or {})
                        .get("initializers") or {}).get("pending") or []]
        if new_pending == old_pending:
            return
        # removal must be prefix-order: the first pending initializer is
        # the only one allowed to complete
        if old_pending and new_pending == old_pending[1:]:
            return
        if not old_pending and new_pending:
            self.deny("cannot add initializers after creation")
        self.deny("initializers must be removed in order, first first")


class PodSecurityPolicyPlugin(AdmissionPlugin):
    """``plugin/pkg/admission/security/podsecuritypolicy``: a pod is
    admitted by the FIRST policy (name order) that allows everything it
    requests — privilege, host namespaces, user range, volume kinds; the
    admitting policy's name is stamped on the pod.  With no policies
    registered the plugin is inert (the cluster hasn't opted into PSP)."""

    name = "PodSecurityPolicy"
    operations = (CREATE,)

    ANNOTATION = "kubernetes.io/psp"

    def handles(self, attrs: Attributes) -> bool:
        return attrs.kind == "Pod" and super().handles(attrs)

    def _violations(self, policy: dict, pod: dict) -> list:
        spec = pod.get("spec") or {}
        pspec = policy.get("spec") or {}
        out = []
        for flag, allowed_key in (("hostPID", "hostPID"), ("hostIPC", "hostIPC"),
                                  ("hostNetwork", "hostNetwork")):
            if spec.get(flag) and not pspec.get(allowed_key):
                out.append(f"{flag} is not allowed")
        run_rule = (pspec.get("runAsUser") or {}).get("rule", "RunAsAny")
        for c in (spec.get("containers") or []) + (spec.get("initContainers") or []):
            sc = c.get("securityContext") or {}
            if sc.get("privileged") and not pspec.get("privileged"):
                out.append(f"privileged container {c.get('name')!r} is not allowed")
            if run_rule == "MustRunAs":
                uid = sc.get("runAsUser")
                lo = (pspec.get("runAsUser") or {}).get("min", 0)
                hi = (pspec.get("runAsUser") or {}).get("max", 1 << 31)
                if uid is None or not (lo <= uid <= hi):
                    out.append(
                        f"container {c.get('name')!r} runAsUser {uid} outside "
                        f"[{lo}, {hi}]")
        allowed_kinds = pspec.get("allowedVolumeKinds")
        if allowed_kinds is None:
            allowed_kinds = ["*"]
        # NOTE: [] is a VALID policy (deny all volumes) — never coerce an
        # empty list to the wildcard
        if "*" not in allowed_kinds:
            for v in spec.get("volumes") or []:
                kind = v.get("diskKind") or ("pvc" if v.get("pvcName") else "")
                if kind and kind not in allowed_kinds:
                    out.append(f"volume kind {kind!r} is not allowed")
        return out

    def validate(self, attrs: Attributes) -> None:
        if attrs.store is None:
            return
        policies, _ = attrs.store.list("PodSecurityPolicy", "")
        if not policies:
            return  # PSP not in use
        failures = {}
        for policy in sorted(policies,
                             key=lambda p: (p.get("metadata") or {}).get("name", "")):
            bad = self._violations(policy, attrs.obj or {})
            pname = (policy.get("metadata") or {}).get("name", "")
            if not bad:
                # stamp the admitting policy (validate runs after admit;
                # the annotation write here is the reference's behavior)
                ((attrs.obj or {}).setdefault("metadata", {})
                 .setdefault("annotations", {}))[self.ANNOTATION] = pname
                return
            failures[pname] = bad[0]
        detail = "; ".join(f"{n}: {m}" for n, m in failures.items())
        self.deny(f"no PodSecurityPolicy admits this pod ({detail})")


class NetworkPolicyValidation(AdmissionPlugin):
    """Validation for the networking group (reference
    ``pkg/apis/networking/validation/validation.go``): the podSelector
    must parse as a label selector, each port needs a TCP/UDP protocol
    and a numeric port in 1-65535 or a named port, and each peer must
    carry exactly one of podSelector / namespaceSelector."""

    name = "NetworkPolicyValidation"
    operations = (CREATE, UPDATE)

    def handles(self, attrs: Attributes) -> bool:
        return attrs.kind == "NetworkPolicy" and super().handles(attrs)

    def _check_selector(self, d, path: str) -> None:
        from ..api import selectors as _sel

        try:
            sel = LabelSelector.from_dict(d)
        except (ValueError, TypeError, KeyError, AttributeError) as e:
            self.deny(f"{path}: invalid selector: {e}")
            return
        ops = (_sel.IN, _sel.NOT_IN, _sel.EXISTS, _sel.DOES_NOT_EXIST,
               _sel.GT, _sel.LT)
        for r in sel.match_expressions:
            if r.operator not in ops:
                self.deny(f"{path}: unknown operator {r.operator!r}")

    def validate(self, attrs: Attributes) -> None:
        spec = (attrs.obj or {}).get("spec") or {}
        if not isinstance(spec, dict):
            self.deny("spec: must be an object")
        # podSelector is REQUIRED (types.go:46 "This field is NOT
        # optional"): an omitted selector must not silently decode to
        # the empty selector and isolate every pod in the namespace
        if not isinstance(spec.get("podSelector"), dict):
            self.deny("spec.podSelector: required field (an explicit {} "
                      "selects all pods in the namespace)")
        self._check_selector(spec.get("podSelector"), "spec.podSelector")
        ingress = spec.get("ingress") or []
        if not isinstance(ingress, list):
            self.deny("spec.ingress: must be a list")
        for i, rule in enumerate(ingress):
            if not isinstance(rule, dict):
                self.deny(f"spec.ingress[{i}]: must be an object")
            ports = rule.get("ports") or []
            peers = rule.get("from") or []
            if not isinstance(ports, list):
                self.deny(f"spec.ingress[{i}].ports: must be a list")
            if not isinstance(peers, list):
                self.deny(f"spec.ingress[{i}].from: must be a list")
            for j, port in enumerate(ports):
                if not isinstance(port, dict):
                    self.deny(f"spec.ingress[{i}].ports[{j}]: "
                              f"must be an object")
                proto = port.get("protocol", "TCP")
                if proto not in ("TCP", "UDP"):
                    self.deny(f"spec.ingress[{i}].ports[{j}].protocol: "
                              f"unsupported value {proto!r}")
                p = port.get("port")
                if p is not None:
                    if isinstance(p, bool) or not isinstance(p, (int, str)):
                        self.deny(f"spec.ingress[{i}].ports[{j}].port: "
                                  f"must be a number or named port")
                    if isinstance(p, int) and not (1 <= p <= 65535):
                        self.deny(f"spec.ingress[{i}].ports[{j}].port: "
                                  f"must be between 1 and 65535")
                    if isinstance(p, str) and not p:
                        self.deny(f"spec.ingress[{i}].ports[{j}].port: "
                                  f"named port must not be empty")
            for j, peer in enumerate(peers):
                if not isinstance(peer, dict):
                    self.deny(f"spec.ingress[{i}].from[{j}]: "
                              f"must be an object")
                has_pod = "podSelector" in peer
                has_ns = "namespaceSelector" in peer
                if has_pod == has_ns:  # both or neither
                    self.deny(f"spec.ingress[{i}].from[{j}]: exactly one "
                              f"of podSelector or namespaceSelector "
                              f"is required")
                sel = peer.get("podSelector") if has_pod else peer.get("namespaceSelector")
                self._check_selector(sel, f"spec.ingress[{i}].from[{j}]")
