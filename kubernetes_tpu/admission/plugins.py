"""Built-in admission plugins.

Capability equivalents of the reference's default plugin set for this era
(``kubeapiserver/options/plugins.go``; implementations under
``plugin/pkg/admission/``):

- NamespaceLifecycle   — ``namespace/lifecycle/admission.go``
- LimitRanger          — ``limitranger/admission.go``
- ServiceAccount       — ``serviceaccount/admission.go``
- DefaultTolerationSeconds — ``defaulttolerationseconds/admission.go``
- LimitPodHardAntiAffinityTopology — ``antiaffinity/admission.go``
- Priority             — ``priority/admission.go`` (PodPriority gate)
- ResourceQuota        — ``resourcequota/admission.go`` (always LAST:
  nothing may mutate the object after usage is charged)
"""

from __future__ import annotations

import logging

from ..api.quantity import Quantity
from ..store.store import NotFoundError
from ..api.types import (CPU, MEMORY, HOSTNAME_LABEL,
    TAINT_NODE_NOT_READY, TAINT_NODE_UNREACHABLE)
from . import plugins_ext as _PluginsExt
from . import quota as quotalib
from .framework import (
    CREATE,
    DELETE,
    AdmissionChain,
    AdmissionDenied,
    AdmissionPlugin,
    Attributes,
)

# Namespaces that always exist and can never be deleted (reference
# ``namespace/lifecycle/admission.go`` immortalNamespaces).
IMMORTAL_NAMESPACES = {"default", "kube-system", "kube-public"}


class PodPrepareForCreate(AdmissionPlugin):
    """Resets client-supplied pod status on create: every pod starts
    Pending (reference ``pkg/registry/core/pod/strategy.go
    PrepareForCreate`` wipes Status).  This also makes the ResourceQuota
    charge/release ledger symmetric — a pod can never enter the cluster
    already terminal, so everything released at delete was charged at
    create."""

    name = "PodPrepareForCreate"
    operations = (CREATE,)

    def admit(self, attrs: Attributes) -> None:
        if attrs.kind != "Pod":
            return
        attrs.obj["status"] = {"phase": "Pending"}


class NamespaceLifecycle(AdmissionPlugin):
    name = "NamespaceLifecycle"
    operations = (CREATE, DELETE)

    def validate(self, attrs: Attributes) -> None:
        from ..api.types import CLUSTER_SCOPED_KINDS

        if attrs.operation == DELETE:
            if attrs.kind == "Namespace" and attrs.name in IMMORTAL_NAMESPACES:
                self.deny(f"namespace {attrs.name} is immortal")
            return
        if attrs.kind in CLUSTER_SCOPED_KINDS or attrs.kind == "Namespace":
            return
        if attrs.namespace in IMMORTAL_NAMESPACES:
            return
        try:
            ns = attrs.store.get("Namespace", "", attrs.namespace)
        except KeyError:
            self.deny(f"namespace {attrs.namespace} not found")
            return
        phase = (ns.get("status") or {}).get("phase", "Active")
        deleting = (ns.get("metadata") or {}).get("deletionRevision") is not None
        if phase == "Terminating" or deleting:
            self.deny(f"namespace {attrs.namespace} is terminating")


class LimitRanger(AdmissionPlugin):
    """Applies LimitRange defaults to pod containers and enforces min/max
    (reference ``limitranger/admission.go``)."""

    name = "LimitRanger"
    operations = (CREATE,)

    def _ranges(self, attrs: Attributes) -> list[dict]:
        items, _ = attrs.store.list("LimitRange", attrs.namespace)
        return items

    def admit(self, attrs: Attributes) -> None:
        if attrs.kind != "Pod":
            return
        for lr in self._ranges(attrs):
            for item in (lr.get("spec") or {}).get("limits") or []:
                if item.get("type", "Container") != "Container":
                    continue
                defaults = item.get("default") or {}
                default_req = item.get("defaultRequest") or {}
                for c in (attrs.obj.get("spec") or {}).get("containers") or []:
                    res = c.setdefault("resources", {})
                    req = res.setdefault("requests", {})
                    lim = res.setdefault("limits", {})
                    for name, v in default_req.items():
                        req.setdefault(name, v)
                    for name, v in defaults.items():
                        lim.setdefault(name, v)
                        # limit defaults also backfill requests (reference:
                        # derived from limit when only default is set)
                        req.setdefault(name, v)

    def validate(self, attrs: Attributes) -> None:
        if attrs.kind != "Pod":
            return
        for lr in self._ranges(attrs):
            for item in (lr.get("spec") or {}).get("limits") or []:
                if item.get("type", "Container") != "Container":
                    continue
                lo = item.get("min") or {}
                hi = item.get("max") or {}
                for c in (attrs.obj.get("spec") or {}).get("containers") or []:
                    res = c.get("resources") or {}
                    req = res.get("requests") or {}
                    lim = res.get("limits") or {}
                    for name, floor in lo.items():
                        got = Quantity(req.get(name, 0))
                        if got < Quantity(floor):
                            self.deny(
                                f"minimum {name} usage per Container is {floor}; "
                                f"container {c.get('name')} requests {got}"
                            )
                    for name, ceiling in hi.items():
                        got = max(
                            Quantity(lim.get(name, 0)), Quantity(req.get(name, 0))
                        )
                        if Quantity(ceiling) < got:
                            self.deny(
                                f"maximum {name} usage per Container is {ceiling}; "
                                f"container {c.get('name')} uses {got}"
                            )


class ServiceAccount(AdmissionPlugin):
    """Defaults ``spec.serviceAccountName`` and requires the referenced
    ServiceAccount to exist (reference ``serviceaccount/admission.go``;
    "default" may be absent — its controller may not have created it yet)."""

    name = "ServiceAccount"
    operations = (CREATE,)

    def admit(self, attrs: Attributes) -> None:
        if attrs.kind != "Pod":
            return
        spec = attrs.obj.setdefault("spec", {})
        if not spec.get("serviceAccountName"):
            spec["serviceAccountName"] = "default"

    def validate(self, attrs: Attributes) -> None:
        if attrs.kind != "Pod":
            return
        name = (attrs.obj.get("spec") or {}).get("serviceAccountName", "default")
        if name == "default":
            return
        try:
            attrs.store.get("ServiceAccount", attrs.namespace, name)
        except KeyError:
            self.deny(f"service account {attrs.namespace}/{name} not found")


class DefaultTolerationSeconds(AdmissionPlugin):
    """Adds default 300s NoExecute tolerations for node.alpha not-ready /
    unreachable taints (reference ``defaulttolerationseconds/admission.go``)."""

    name = "DefaultTolerationSeconds"
    operations = (CREATE,)

    NOT_READY = TAINT_NODE_NOT_READY
    UNREACHABLE = TAINT_NODE_UNREACHABLE
    DEFAULT_SECONDS = 300

    def admit(self, attrs: Attributes) -> None:
        if attrs.kind != "Pod":
            return
        spec = attrs.obj.setdefault("spec", {})
        tolerations = spec.setdefault("tolerations", [])
        keys = {t.get("key") for t in tolerations}
        for key in (self.NOT_READY, self.UNREACHABLE):
            if key not in keys:
                tolerations.append({
                    "key": key,
                    "operator": "Exists",
                    "effect": "NoExecute",
                    "tolerationSeconds": self.DEFAULT_SECONDS,
                })


class LimitPodHardAntiAffinityTopology(AdmissionPlugin):
    """Denies required pod anti-affinity with a topology key other than
    hostname (reference ``antiaffinity/admission.go``)."""

    name = "LimitPodHardAntiAffinityTopology"
    operations = (CREATE,)

    def validate(self, attrs: Attributes) -> None:
        if attrs.kind != "Pod":
            return
        affinity = (attrs.obj.get("spec") or {}).get("affinity") or {}
        for term in affinity.get("podAntiAffinityRequired") or []:
            key = term.get("topologyKey", "")
            if key and key != HOSTNAME_LABEL:
                self.deny(
                    "required pod anti-affinity has topologyKey "
                    f"{key}; only {HOSTNAME_LABEL} is allowed"
                )


class Priority(AdmissionPlugin):
    """Resolves ``priorityClassName`` into ``spec.priority`` (reference
    ``priority/admission.go``, PodPriority feature)."""

    name = "Priority"
    operations = (CREATE,)

    def admit(self, attrs: Attributes) -> None:
        if attrs.kind != "Pod":
            return
        spec = attrs.obj.setdefault("spec", {})
        cls_name = spec.get("priorityClassName", "")
        if cls_name:
            try:
                pc = attrs.store.get("PriorityClass", "", cls_name)
            except KeyError:
                self.deny(f"no PriorityClass with name {cls_name} was found")
                return
            spec["priority"] = int(pc.get("value", 0))
            return
        if spec.get("priority"):
            # non-zero priority stands; 0 means "unset" on this wire form
            # (PodSpec always serializes the field, so absence can't signal)
            return
        for pc in attrs.store.list("PriorityClass", None)[0]:
            if pc.get("globalDefault"):
                spec["priority"] = int(pc.get("value", 0))
                spec["priorityClassName"] = pc["metadata"]["name"]
                return


class ResourceQuota(AdmissionPlugin):
    """Synchronous quota enforcement: charges usage against every matching
    ResourceQuota in the namespace with a CAS on ``status.used`` before the
    object is stored; releases it on delete.  Runs LAST (reference
    ``resourcequota/admission.go`` — the plugin registry pins it to the end
    so later mutation can't dodge the ledger).  Leaked charges from failed
    writes are healed by the quota controller's full recalculation."""

    name = "ResourceQuota"
    operations = (CREATE, DELETE)

    def validate(self, attrs: Attributes) -> None:
        release = attrs.operation == DELETE
        obj = attrs.obj if attrs.operation == CREATE else attrs.old_obj
        # Deleting a TERMINAL pod releases nothing here: its usage was
        # already dropped by the quota controller's churn-driven resync at
        # the Succeeded/Failed transition, and decrementing again would
        # deflate status.used below the truth (over-admission).  Releasing
        # only live usage mirrors the reference, where admission never
        # lowers used past what replenishment computed; the controller
        # MUST run alongside this plugin to reclaim terminal-pod usage.
        usage = quotalib.usage_for(attrs.kind, obj)
        if not usage:
            return
        quotas, _ = attrs.store.list("ResourceQuota", attrs.namespace)
        charged: list[dict] = []
        for rq in quotas:
            scopes = (rq.get("spec") or {}).get("scopes") or []
            if not quotalib.matches_scopes(scopes, attrs.kind, obj):
                continue
            try:
                self._charge(attrs, rq, usage, release=release)
            except NotFoundError:
                # quota vanished between list and CAS: it constrains nothing
                # anymore, skip it
                continue
            except Exception:
                # deny (or any CAS failure) on a later quota: undo charges
                # already applied to earlier quotas so the failed write
                # leaves no quota inflated; a failed undo must not mask the
                # original error — the controller resync heals the leak
                for prev in charged:
                    try:
                        self._charge(attrs, prev, usage, release=True)
                    except Exception as undo_err:  # noqa: BLE001
                        # an inflated quota self-heals at the controller's
                        # next resync; warn so the interim over-restriction
                        # has a visible cause (the ORIGINAL error re-raises
                        # below — the undo failure must not mask it)
                        logging.getLogger("kubernetes_tpu.admission").warning(
                            "quota undo failed for %s/%s (%s); controller "
                            "resync will reconcile",
                            attrs.namespace, prev["metadata"]["name"],
                            undo_err)
                raise
            if not release:
                charged.append(rq)

    def _charge(self, attrs: Attributes, rq: dict, usage, release: bool) -> None:
        name = rq["metadata"]["name"]
        plugin = self

        def _apply(cur: dict) -> dict:
            status = cur.setdefault("status", {})
            hard = {k: Quantity(v) for k, v in (status.get("hard") or (cur.get("spec") or {}).get("hard") or {}).items()}
            used = {k: Quantity(v) for k, v in (status.get("used") or {}).items()}
            if release:
                new_used = quotalib.sub_usage(used, usage)
            else:
                new_used = quotalib.add_usage(used, usage)
                over = quotalib.exceeds(hard, new_used)
                if over:
                    plugin.deny(
                        f"exceeded quota: {name}, requested: "
                        + ",".join(f"{r}={usage.get(r)}" for r in over if r in usage)
                        + ", limited: "
                        + ",".join(f"{r}={hard[r]}" for r in over)
                    )
            status["used"] = {k: str(v) for k, v in new_used.items()}
            return cur

        attrs.store.guaranteed_update("ResourceQuota", attrs.namespace, name, _apply)


def default_chain() -> AdmissionChain:
    """The default plugin order (quota last, like the reference's
    ``plugins.go`` recommended order)."""
    return AdmissionChain([
        PodPrepareForCreate(),
        NamespaceLifecycle(),
        LimitRanger(),
        ServiceAccount(),
        _PluginsExt.ServiceIPAllocator(),
        _PluginsExt.DefaultStorageClass(),
        _PluginsExt.PodPreset(),
        DefaultTolerationSeconds(),
        LimitPodHardAntiAffinityTopology(),
        Priority(),
        _PluginsExt.DenyEscalatingExec(),
        # inert until PodSecurityPolicy objects exist (opt-in like the
        # reference's plugin enablement)
        _PluginsExt.PodSecurityPolicyPlugin(),
        _PluginsExt.NetworkPolicyValidation(),
        ResourceQuota(),
    ])
