"""Quota usage evaluators (reference ``pkg/quota`` — ``Evaluator`` per
group-kind, ``pkg/quota/evaluator/core/pods.go`` for pod compute usage).

``usage_for(kind, obj)`` maps an object to the quota resources it consumes;
``add_usage``/``sub_usage`` are the ledger arithmetic used by both the
ResourceQuota admission plugin (synchronous enforcement) and the quota
controller (asynchronous full recalculation).
"""

from __future__ import annotations

from typing import Optional

from ..api.quantity import Quantity
from ..api.types import CPU, MEMORY

# quota resource names (reference pkg/api/types.go ResourceName consts)
PODS = "pods"
REQUESTS_CPU = "requests.cpu"
REQUESTS_MEMORY = "requests.memory"
LIMITS_CPU = "limits.cpu"
LIMITS_MEMORY = "limits.memory"

# kinds counted with simple object-count quota resources
# (reference: services, secrets, configmaps, replicationcontrollers,
# resourcequotas, persistentvolumeclaims all countable)
COUNTED_KINDS = {
    "Service": "services",
    "Secret": "secrets",
    "ConfigMap": "configmaps",
    "ReplicaSet": "count/replicasets",
    "Deployment": "count/deployments",
    "Job": "count/jobs",
    "PersistentVolumeClaim": "persistentvolumeclaims",
}


def _pod_terminal(obj: dict) -> bool:
    phase = (obj.get("status") or {}).get("phase", "Pending")
    return phase in ("Succeeded", "Failed")


def usage_for(kind: str, obj: Optional[dict]) -> dict[str, Quantity]:
    """Quota resources consumed by one object (empty if not quota-tracked).

    Pod usage follows the reference's rule (``evaluator/core/pods.go``):
    terminal pods consume nothing; cpu/memory usage = sum of container
    requests (and limits for the limits.* resources).  Terminal-pod usage
    is reclaimed by the quota CONTROLLER at the phase transition, never by
    the admission delete path (see ResourceQuota.validate)."""
    if obj is None:
        return {}
    if kind == "Pod":
        if _pod_terminal(obj):
            return {}
        usage: dict[str, Quantity] = {PODS: Quantity(1)}
        req_cpu = Quantity(0)
        req_mem = Quantity(0)
        lim_cpu = Quantity(0)
        lim_mem = Quantity(0)
        for c in (obj.get("spec") or {}).get("containers") or []:
            res = c.get("resources") or {}
            req = res.get("requests") or {}
            lim = res.get("limits") or {}
            req_cpu += Quantity(req.get(CPU, 0))
            req_mem += Quantity(req.get(MEMORY, 0))
            lim_cpu += Quantity(lim.get(CPU, 0))
            lim_mem += Quantity(lim.get(MEMORY, 0))
        if not req_cpu.is_zero():
            usage[REQUESTS_CPU] = req_cpu
            usage[CPU] = req_cpu  # bare "cpu" aliases requests.cpu
        if not req_mem.is_zero():
            usage[REQUESTS_MEMORY] = req_mem
            usage[MEMORY] = req_mem
        if not lim_cpu.is_zero():
            usage[LIMITS_CPU] = lim_cpu
        if not lim_mem.is_zero():
            usage[LIMITS_MEMORY] = lim_mem
        return usage
    counted = COUNTED_KINDS.get(kind)
    if counted:
        return {counted: Quantity(1)}
    return {}


def matches_scopes(scopes: list[str], kind: str, obj: Optional[dict]) -> bool:
    """Reference quota scopes (``pkg/quota/evaluator/core/pods.go``
    podMatchesScopeFunc): BestEffort / NotBestEffort / Terminating /
    NotTerminating select which pods a scoped quota tracks."""
    if not scopes:
        return True
    if kind != "Pod" or obj is None:
        return False
    best_effort = _is_best_effort(obj)
    terminating = ((obj.get("spec") or {}).get("activeDeadlineSeconds")) is not None
    for scope in scopes:
        if scope == "BestEffort" and not best_effort:
            return False
        if scope == "NotBestEffort" and best_effort:
            return False
        if scope == "Terminating" and not terminating:
            return False
        if scope == "NotTerminating" and terminating:
            return False
    return True


def _is_best_effort(obj: dict) -> bool:
    for c in (obj.get("spec") or {}).get("containers") or []:
        res = c.get("resources") or {}
        for section in ("requests", "limits"):
            for name in (CPU, MEMORY):
                if not Quantity((res.get(section) or {}).get(name, 0)).is_zero():
                    return False
    return True


def add_usage(a: dict[str, Quantity], b: dict[str, Quantity]) -> dict[str, Quantity]:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, Quantity(0)) + v
    return out


def sub_usage(a: dict[str, Quantity], b: dict[str, Quantity]) -> dict[str, Quantity]:
    out = dict(a)
    for k, v in b.items():
        cur = out.get(k, Quantity(0)) - v
        out[k] = cur if Quantity(0) < cur else Quantity(0)
    return out


def exceeds(hard: dict[str, Quantity], used: dict[str, Quantity]) -> list[str]:
    """Resources where used > hard (only resources the quota constrains)."""
    over = []
    for name, ceiling in hard.items():
        if ceiling < used.get(name, Quantity(0)):
            over.append(name)
    return over
