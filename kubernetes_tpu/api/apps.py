"""Workload API types beyond Deployment/ReplicaSet: Job, CronJob,
DaemonSet, StatefulSet.

Capability equivalents of the reference's internal types in
``pkg/apis/batch/types.go`` (Job :51, CronJob :192) and
``pkg/apis/apps/types.go`` / ``pkg/apis/extensions/types.go``
(StatefulSet, DaemonSet) at the depth the controllers reconcile.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional

from .meta import ObjectMeta
from .selectors import LabelSelector
from .types import PodTemplateSpec, register_kind


@register_kind
@dataclass
class Job:
    """Run-to-completion workload (reference ``pkg/apis/batch/types.go:51``,
    controller ``pkg/controller/job/jobcontroller.go``)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    parallelism: int = 1
    completions: Optional[int] = 1  # None => work-queue style
    backoff_limit: int = 6
    active_deadline_seconds: Optional[int] = None
    selector: LabelSelector = field(default_factory=LabelSelector)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    status_active: int = 0
    status_succeeded: int = 0
    status_failed: int = 0
    status_conditions: list[dict] = field(default_factory=list)  # Complete | Failed
    status_start_time: float = 0.0  # controller clock at first sync

    KIND = "Job"

    @property
    def complete(self) -> bool:
        return any(c.get("type") == "Complete" and c.get("status") == "True"
                   for c in self.status_conditions)

    @property
    def failed(self) -> bool:
        return any(c.get("type") == "Failed" and c.get("status") == "True"
                   for c in self.status_conditions)

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "parallelism": self.parallelism,
                "completions": self.completions,
                "backoffLimit": self.backoff_limit,
                "activeDeadlineSeconds": self.active_deadline_seconds,
                "selector": self.selector.to_dict(),
                "template": self.template.to_dict(),
            },
            "status": {
                "active": self.status_active,
                "succeeded": self.status_succeeded,
                "failed": self.status_failed,
                "conditions": list(self.status_conditions),
                "startTime": self.status_start_time,
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Job":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        comp = spec.get("completions", 1)
        ads = spec.get("activeDeadlineSeconds")
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            parallelism=int(spec.get("parallelism", 1)),
            completions=None if comp is None else int(comp),
            backoff_limit=int(spec.get("backoffLimit", 6)),
            active_deadline_seconds=None if ads is None else int(ads),
            selector=LabelSelector.from_dict(spec.get("selector")),
            template=PodTemplateSpec.from_dict(spec.get("template")),
            status_active=int(status.get("active", 0)),
            status_succeeded=int(status.get("succeeded", 0)),
            status_failed=int(status.get("failed", 0)),
            status_conditions=list(status.get("conditions") or []),
            status_start_time=float(status.get("startTime", 0.0)),
        )


@register_kind
@dataclass
class CronJob:
    """Time-based Job creator (reference ``pkg/apis/batch/types.go:192``
    CronJob, controller ``pkg/controller/cronjob/cronjob_controller.go``).

    ``schedule`` is a 5-field cron expression; the controller evaluates it
    against the injected clock."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    schedule: str = "* * * * *"
    concurrency_policy: str = "Allow"  # Allow | Forbid | Replace
    suspend: bool = False
    starting_deadline_seconds: Optional[int] = None
    job_template: Optional[dict] = None  # Job spec dict (template for spawned Jobs)
    successful_jobs_history_limit: int = 3
    failed_jobs_history_limit: int = 1
    status_active: list[str] = field(default_factory=list)  # names of running Jobs
    status_last_schedule_time: float = 0.0

    KIND = "CronJob"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "schedule": self.schedule,
                "concurrencyPolicy": self.concurrency_policy,
                "suspend": self.suspend,
                "startingDeadlineSeconds": self.starting_deadline_seconds,
                "jobTemplate": copy.deepcopy(self.job_template),
                "successfulJobsHistoryLimit": self.successful_jobs_history_limit,
                "failedJobsHistoryLimit": self.failed_jobs_history_limit,
            },
            "status": {
                "active": list(self.status_active),
                "lastScheduleTime": self.status_last_schedule_time,
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CronJob":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        sds = spec.get("startingDeadlineSeconds")
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            schedule=spec.get("schedule", "* * * * *"),
            concurrency_policy=spec.get("concurrencyPolicy", "Allow"),
            suspend=bool(spec.get("suspend", False)),
            starting_deadline_seconds=None if sds is None else int(sds),
            job_template=copy.deepcopy(spec.get("jobTemplate")),
            successful_jobs_history_limit=int(spec.get("successfulJobsHistoryLimit", 3)),
            failed_jobs_history_limit=int(spec.get("failedJobsHistoryLimit", 1)),
            status_active=list(status.get("active") or []),
            status_last_schedule_time=float(status.get("lastScheduleTime", 0.0)),
        )


@register_kind
@dataclass
class DaemonSet:
    """One pod per matching node (reference ``pkg/apis/extensions/types.go``
    DaemonSet; controller ``pkg/controller/daemon/daemoncontroller.go`` —
    notably it does its OWN scheduling with the scheduler's predicates)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    selector: LabelSelector = field(default_factory=LabelSelector)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    update_strategy: str = "RollingUpdate"  # RollingUpdate | OnDelete
    max_unavailable: int = 1
    status_desired: int = 0
    status_current: int = 0
    status_ready: int = 0
    status_updated: int = 0
    status_mis_scheduled: int = 0

    KIND = "DaemonSet"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "selector": self.selector.to_dict(),
                "template": self.template.to_dict(),
                "updateStrategy": self.update_strategy,
                "maxUnavailable": self.max_unavailable,
            },
            "status": {
                "desiredNumberScheduled": self.status_desired,
                "currentNumberScheduled": self.status_current,
                "numberReady": self.status_ready,
                "updatedNumberScheduled": self.status_updated,
                "numberMisscheduled": self.status_mis_scheduled,
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "DaemonSet":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            selector=LabelSelector.from_dict(spec.get("selector")),
            template=PodTemplateSpec.from_dict(spec.get("template")),
            update_strategy=spec.get("updateStrategy", "RollingUpdate"),
            max_unavailable=int(spec.get("maxUnavailable", 1)),
            status_desired=int(status.get("desiredNumberScheduled", 0)),
            status_current=int(status.get("currentNumberScheduled", 0)),
            status_ready=int(status.get("numberReady", 0)),
            status_updated=int(status.get("updatedNumberScheduled", 0)),
            status_mis_scheduled=int(status.get("numberMisscheduled", 0)),
        )


@register_kind
@dataclass
class StatefulSet:
    """Ordered, identity-preserving replicas (reference
    ``pkg/apis/apps/types.go`` StatefulSet; controller
    ``pkg/controller/statefulset/stateful_set.go``).  Pods are named
    ``<set>-<ordinal>`` and created/deleted in ordinal order."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    replicas: int = 1
    selector: LabelSelector = field(default_factory=LabelSelector)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    service_name: str = ""
    pod_management_policy: str = "OrderedReady"  # OrderedReady | Parallel
    update_strategy: str = "RollingUpdate"  # RollingUpdate | OnDelete
    partition: int = 0
    status_replicas: int = 0
    status_ready_replicas: int = 0
    status_current_replicas: int = 0
    status_updated_replicas: int = 0
    status_observed_generation: int = 0

    KIND = "StatefulSet"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "replicas": self.replicas,
                "selector": self.selector.to_dict(),
                "template": self.template.to_dict(),
                "serviceName": self.service_name,
                "podManagementPolicy": self.pod_management_policy,
                "updateStrategy": self.update_strategy,
                "partition": self.partition,
            },
            "status": {
                "replicas": self.status_replicas,
                "readyReplicas": self.status_ready_replicas,
                "currentReplicas": self.status_current_replicas,
                "updatedReplicas": self.status_updated_replicas,
                "observedGeneration": self.status_observed_generation,
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "StatefulSet":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            replicas=int(spec.get("replicas", 1)),
            selector=LabelSelector.from_dict(spec.get("selector")),
            template=PodTemplateSpec.from_dict(spec.get("template")),
            service_name=spec.get("serviceName", ""),
            pod_management_policy=spec.get("podManagementPolicy", "OrderedReady"),
            update_strategy=spec.get("updateStrategy", "RollingUpdate"),
            partition=int(spec.get("partition", 0)),
            status_replicas=int(status.get("replicas", 0)),
            status_ready_replicas=int(status.get("readyReplicas", 0)),
            status_current_replicas=int(status.get("currentReplicas", 0)),
            status_updated_replicas=int(status.get("updatedReplicas", 0)),
            status_observed_generation=int(status.get("observedGeneration", 0)),
        )
