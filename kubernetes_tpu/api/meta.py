"""Object metadata — the capability of the reference's ``metav1.ObjectMeta``
(``staging/src/k8s.io/apimachinery/pkg/apis/meta/v1/types.go``).

Every stored object carries name/namespace/uid/resourceVersion/labels/
annotations plus ownerReferences and deletion bookkeeping.  Serialization is
plain dicts (JSON-shaped); the store assigns ``uid`` and maintains
``resource_version`` the way etcd maintains ``mod_revision``.
"""

from __future__ import annotations

import dataclasses
import itertools
from dataclasses import dataclass, field
from typing import Optional

_uid_counter = itertools.count(1)


def new_uid() -> str:
    return f"uid-{next(_uid_counter):08d}"


@dataclass
class OwnerReference:
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = False

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "OwnerReference":
        return cls(
            kind=d.get("kind", ""),
            name=d.get("name", ""),
            uid=d.get("uid", ""),
            controller=bool(d.get("controller", False)),
        )


@dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    resource_version: int = 0
    labels: dict[str, str] = field(default_factory=dict)
    annotations: dict[str, str] = field(default_factory=dict)
    owner_references: list[OwnerReference] = field(default_factory=list)
    creation_revision: int = 0
    deletion_revision: Optional[int] = None  # tombstone for graceful deletion
    generation: int = 0
    # Deletion is blocked until every finalizer is removed (reference
    # registry/generic/registry/store.go:977 graceful deletion + finalizers;
    # used by the namespace controller and the garbage collector).
    finalizers: list[str] = field(default_factory=list)

    @property
    def key(self) -> str:
        """namespace/name — the store key suffix (like etcd key paths)."""
        return f"{self.namespace}/{self.name}" if self.namespace else self.name

    def controller_ref(self) -> Optional[OwnerReference]:
        for ref in self.owner_references:
            if ref.controller:
                return ref
        return None

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "namespace": self.namespace,
            "uid": self.uid,
            "resourceVersion": self.resource_version,
            "creationRevision": self.creation_revision,
            "generation": self.generation,
        }
        if self.labels:
            d["labels"] = dict(self.labels)
        if self.annotations:
            d["annotations"] = dict(self.annotations)
        if self.owner_references:
            d["ownerReferences"] = [r.to_dict() for r in self.owner_references]
        if self.deletion_revision is not None:
            d["deletionRevision"] = self.deletion_revision
        if self.finalizers:
            d["finalizers"] = list(self.finalizers)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ObjectMeta":
        return cls(
            name=d.get("name", ""),
            namespace=d.get("namespace", "default"),
            uid=d.get("uid", ""),
            resource_version=int(d.get("resourceVersion", 0)),
            creation_revision=int(d.get("creationRevision", 0)),
            labels=dict(d.get("labels") or {}),
            annotations=dict(d.get("annotations") or {}),
            owner_references=[
                OwnerReference.from_dict(r) for r in d.get("ownerReferences") or []
            ],
            deletion_revision=d.get("deletionRevision"),
            generation=int(d.get("generation", 0)),
            finalizers=list(d.get("finalizers") or []),
        )
