"""Namespace-, config-, quota-, storage- and autoscaling-related API types.

Capability equivalents of the reference internal types:

- Namespace, Secret, ConfigMap, ServiceAccount, Endpoints —
  ``pkg/api/types.go`` (Namespace ~:3010, Secret ~:3330, ConfigMap,
  ServiceAccount ~:2960, Endpoints ~:2480)
- ResourceQuota / LimitRange — ``pkg/api/types.go`` (~:3180 / ~:3120),
  enforced by admission (``plugin/pkg/admission/resourcequota``,
  ``limitranger``) + usage recalculated by the quota controller
- PodDisruptionBudget — ``pkg/apis/policy/types.go``, consumed by the
  eviction subresource
- HorizontalPodAutoscaler — ``pkg/apis/autoscaling/types.go``
- PersistentVolume / PersistentVolumeClaim — ``pkg/api/types.go``
  (~:380 / ~:450), bound by ``pkg/controller/volume/persistentvolume``
- PriorityClass — ``pkg/apis/scheduling/types.go`` (PodPriority gate)
- CertificateSigningRequest — ``pkg/apis/certificates/types.go``
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from .meta import ObjectMeta
from .quantity import Quantity
from .selectors import LabelSelector
from .types import (
    ZONE_LABEL,
    _res_from_dict,
    _res_to_dict,
    register_cluster_scoped as _register_cluster_scoped,
    register_kind,
)


@_register_cluster_scoped
@dataclass
class Namespace:
    """Namespace with phase + finalizers (reference ``pkg/api/types.go``
    Namespace; lifecycle in ``pkg/controller/namespace``)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    phase: str = "Active"  # Active | Terminating
    spec_finalizers: list[str] = field(default_factory=lambda: ["kubernetes"])

    KIND = "Namespace"

    def __post_init__(self):
        self.meta.namespace = ""

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {"finalizers": list(self.spec_finalizers)},
            "status": {"phase": self.phase},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Namespace":
        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        meta.namespace = ""
        return cls(
            meta=meta,
            phase=(d.get("status") or {}).get("phase", "Active"),
            spec_finalizers=list((d.get("spec") or {}).get("finalizers") or []),
        )


@register_kind
@dataclass
class Secret:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    type: str = "Opaque"
    data: dict[str, str] = field(default_factory=dict)  # values pre-encoded

    KIND = "Secret"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "type": self.type,
            "data": dict(self.data),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Secret":
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            type=d.get("type", "Opaque"),
            data=dict(d.get("data") or {}),
        )


@register_kind
@dataclass
class ConfigMap:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    data: dict[str, str] = field(default_factory=dict)

    KIND = "ConfigMap"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "data": dict(self.data),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ConfigMap":
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            data=dict(d.get("data") or {}),
        )


@register_kind
@dataclass
class ServiceAccount:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    secrets: list[str] = field(default_factory=list)  # token Secret names

    KIND = "ServiceAccount"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "secrets": list(self.secrets),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ServiceAccount":
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            secrets=list(d.get("secrets") or []),
        )


@dataclass
class EndpointAddress:
    ip: str = ""
    node_name: str = ""
    target_pod: str = ""  # namespace/name of backing pod

    def to_dict(self) -> dict:
        return {"ip": self.ip, "nodeName": self.node_name, "targetPod": self.target_pod}

    @classmethod
    def from_dict(cls, d: dict) -> "EndpointAddress":
        return cls(
            ip=d.get("ip", ""),
            node_name=d.get("nodeName", ""),
            target_pod=d.get("targetPod", ""),
        )


@dataclass
class EndpointPort:
    name: str = ""
    port: int = 0
    protocol: str = "TCP"

    def to_dict(self) -> dict:
        return {"name": self.name, "port": self.port, "protocol": self.protocol}

    @classmethod
    def from_dict(cls, d: dict) -> "EndpointPort":
        return cls(
            name=d.get("name", ""),
            port=int(d.get("port", 0)),
            protocol=d.get("protocol", "TCP"),
        )


@dataclass
class EndpointSubset:
    addresses: list[EndpointAddress] = field(default_factory=list)
    not_ready_addresses: list[EndpointAddress] = field(default_factory=list)
    ports: list[EndpointPort] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "addresses": [a.to_dict() for a in self.addresses],
            "notReadyAddresses": [a.to_dict() for a in self.not_ready_addresses],
            "ports": [p.to_dict() for p in self.ports],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "EndpointSubset":
        return cls(
            addresses=[EndpointAddress.from_dict(a) for a in d.get("addresses") or []],
            not_ready_addresses=[
                EndpointAddress.from_dict(a) for a in d.get("notReadyAddresses") or []
            ],
            ports=[EndpointPort.from_dict(p) for p in d.get("ports") or []],
        )


@register_kind
@dataclass
class Endpoints:
    """Service backend membership (reference ``pkg/api/types.go`` Endpoints;
    maintained by ``pkg/controller/endpoint``)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    subsets: list[EndpointSubset] = field(default_factory=list)

    KIND = "Endpoints"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "subsets": [s.to_dict() for s in self.subsets],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Endpoints":
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            subsets=[EndpointSubset.from_dict(s) for s in d.get("subsets") or []],
        )


@register_kind
@dataclass
class ResourceQuota:
    """Per-namespace aggregate limits; ``hard`` is the ceiling, ``used`` is
    maintained by admission + the quota controller."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    hard: dict[str, Quantity] = field(default_factory=dict)
    used: dict[str, Quantity] = field(default_factory=dict)
    scopes: list[str] = field(default_factory=list)  # e.g. BestEffort, NotBestEffort

    KIND = "ResourceQuota"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {"hard": _res_to_dict(self.hard), "scopes": list(self.scopes)},
            "status": {"hard": _res_to_dict(self.hard), "used": _res_to_dict(self.used)},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ResourceQuota":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            hard=_res_from_dict(spec.get("hard")),
            used=_res_from_dict(status.get("used")),
            scopes=list(spec.get("scopes") or []),
        )


@dataclass
class LimitRangeItem:
    type: str = "Container"  # Container | Pod
    max: dict[str, Quantity] = field(default_factory=dict)
    min: dict[str, Quantity] = field(default_factory=dict)
    default: dict[str, Quantity] = field(default_factory=dict)  # default limits
    default_request: dict[str, Quantity] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "max": _res_to_dict(self.max),
            "min": _res_to_dict(self.min),
            "default": _res_to_dict(self.default),
            "defaultRequest": _res_to_dict(self.default_request),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LimitRangeItem":
        return cls(
            type=d.get("type", "Container"),
            max=_res_from_dict(d.get("max")),
            min=_res_from_dict(d.get("min")),
            default=_res_from_dict(d.get("default")),
            default_request=_res_from_dict(d.get("defaultRequest")),
        )


@register_kind
@dataclass
class PodPreset:
    """Pod injection policy (reference ``pkg/apis/settings/types.go``;
    applied by the PodPreset admission plugin to matching pods at
    create)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    selector: LabelSelector = field(default_factory=LabelSelector)
    env: dict = field(default_factory=dict)
    volumes: list = field(default_factory=list)  # wire-form volume dicts

    KIND = "PodPreset"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "selector": self.selector.to_dict(),
                "env": dict(self.env),
                "volumes": [dict(v) for v in self.volumes],
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PodPreset":
        spec = d.get("spec") or {}
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            selector=LabelSelector.from_dict(spec.get("selector")),
            env=dict(spec.get("env") or {}),
            volumes=[dict(v) for v in spec.get("volumes") or []],
        )


@register_kind
@dataclass
class LimitRange:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    limits: list[LimitRangeItem] = field(default_factory=list)

    KIND = "LimitRange"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {"limits": [l.to_dict() for l in self.limits]},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "LimitRange":
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            limits=[
                LimitRangeItem.from_dict(l)
                for l in (d.get("spec") or {}).get("limits") or []
            ],
        )


@register_kind
@dataclass
class PodDisruptionBudget:
    """Voluntary-eviction budget (reference ``pkg/apis/policy/types.go``;
    status maintained by ``pkg/controller/disruption``)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    min_available: int = 0
    selector: LabelSelector = field(default_factory=LabelSelector)
    status_disruptions_allowed: int = 0
    status_current_healthy: int = 0
    status_desired_healthy: int = 0
    status_expected_pods: int = 0

    KIND = "PodDisruptionBudget"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "minAvailable": self.min_available,
                "selector": self.selector.to_dict(),
            },
            "status": {
                "disruptionsAllowed": self.status_disruptions_allowed,
                "currentHealthy": self.status_current_healthy,
                "desiredHealthy": self.status_desired_healthy,
                "expectedPods": self.status_expected_pods,
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PodDisruptionBudget":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            min_available=int(spec.get("minAvailable", 0)),
            selector=LabelSelector.from_dict(spec.get("selector")),
            status_disruptions_allowed=int(status.get("disruptionsAllowed", 0)),
            status_current_healthy=int(status.get("currentHealthy", 0)),
            status_desired_healthy=int(status.get("desiredHealthy", 0)),
            status_expected_pods=int(status.get("expectedPods", 0)),
        )


@register_kind
@dataclass
class HorizontalPodAutoscaler:
    """Scale a target workload on observed utilization (reference
    ``pkg/apis/autoscaling/types.go``; controller
    ``pkg/controller/podautoscaler/horizontal.go``)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    target_kind: str = "Deployment"
    target_name: str = ""
    min_replicas: int = 1
    max_replicas: int = 1
    target_cpu_utilization: int = 80  # percent of requests
    status_current_replicas: int = 0
    status_desired_replicas: int = 0
    status_current_utilization: int = 0
    status_last_scale_time: float = 0.0

    KIND = "HorizontalPodAutoscaler"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "scaleTargetRef": {"kind": self.target_kind, "name": self.target_name},
                "minReplicas": self.min_replicas,
                "maxReplicas": self.max_replicas,
                "targetCPUUtilizationPercentage": self.target_cpu_utilization,
            },
            "status": {
                "currentReplicas": self.status_current_replicas,
                "desiredReplicas": self.status_desired_replicas,
                "currentCPUUtilizationPercentage": self.status_current_utilization,
                "lastScaleTime": self.status_last_scale_time,
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "HorizontalPodAutoscaler":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        ref = spec.get("scaleTargetRef") or {}
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            target_kind=ref.get("kind", "Deployment"),
            target_name=ref.get("name", ""),
            min_replicas=int(spec.get("minReplicas", 1)),
            max_replicas=int(spec.get("maxReplicas", 1)),
            target_cpu_utilization=int(spec.get("targetCPUUtilizationPercentage", 80)),
            status_current_replicas=int(status.get("currentReplicas", 0)),
            status_desired_replicas=int(status.get("desiredReplicas", 0)),
            status_current_utilization=int(
                status.get("currentCPUUtilizationPercentage", 0)
            ),
            status_last_scale_time=float(status.get("lastScaleTime", 0.0)),
        )


@_register_cluster_scoped
@dataclass
class PersistentVolume:
    """Cluster storage resource (reference ``pkg/api/types.go`` ~:380;
    bound by the PV controller's claim↔volume matching)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    capacity: dict[str, Quantity] = field(default_factory=dict)  # {"storage": ...}
    access_modes: list[str] = field(default_factory=lambda: ["ReadWriteOnce"])
    storage_class: str = ""
    zone: str = ""  # topology constraint (NoVolumeZoneConflict)
    # Local-volume pinning (NoVolumeNodeConflict, reference
    # predicates.go:1323 via the volume.alpha node-affinity annotation):
    node_affinity: "object" = None  # Optional[selectors.NodeSelector]
    reclaim_policy: str = "Retain"  # Retain | Delete | Recycle
    phase: str = "Available"  # Available | Bound | Released | Failed
    claim_ref: str = ""  # namespace/name of bound PVC

    KIND = "PersistentVolume"

    def __post_init__(self):
        self.meta.namespace = ""

    def to_dict(self) -> dict:
        d = {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "capacity": _res_to_dict(self.capacity),
                "accessModes": list(self.access_modes),
                "storageClassName": self.storage_class,
                "reclaimPolicy": self.reclaim_policy,
            },
            "status": {"phase": self.phase, "claimRef": self.claim_ref},
        }
        if self.node_affinity is not None:
            d["spec"]["nodeAffinity"] = self.node_affinity.to_dict()
        if self.zone:
            d["metadata"].setdefault("labels", {})[ZONE_LABEL] = self.zone
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "PersistentVolume":
        from .selectors import NodeSelector

        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        meta.namespace = ""
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return cls(
            meta=meta,
            capacity=_res_from_dict(spec.get("capacity")),
            access_modes=list(spec.get("accessModes") or ["ReadWriteOnce"]),
            storage_class=spec.get("storageClassName", ""),
            zone=meta.labels.get(ZONE_LABEL, ""),
            node_affinity=NodeSelector.from_dict(spec.get("nodeAffinity")),
            reclaim_policy=spec.get("reclaimPolicy", "Retain"),
            phase=status.get("phase", "Available"),
            claim_ref=status.get("claimRef", ""),
        )


@register_kind
@dataclass
class PersistentVolumeClaim:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    request_storage: Quantity = field(default_factory=lambda: Quantity(0))
    access_modes: list[str] = field(default_factory=lambda: ["ReadWriteOnce"])
    storage_class: str = ""
    phase: str = "Pending"  # Pending | Bound | Lost
    volume_name: str = ""  # bound PV name

    KIND = "PersistentVolumeClaim"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "resources": {"requests": {"storage": str(self.request_storage)}},
                "accessModes": list(self.access_modes),
                "storageClassName": self.storage_class,
                "volumeName": self.volume_name,
            },
            "status": {"phase": self.phase},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PersistentVolumeClaim":
        spec = d.get("spec") or {}
        req = ((spec.get("resources") or {}).get("requests") or {}).get("storage", 0)
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            request_storage=Quantity(req),
            access_modes=list(spec.get("accessModes") or ["ReadWriteOnce"]),
            storage_class=spec.get("storageClassName", ""),
            phase=(d.get("status") or {}).get("phase", "Pending"),
            volume_name=spec.get("volumeName", ""),
        )


@_register_cluster_scoped
@dataclass
class StorageClass:
    """Dynamic-provisioning template (reference ``pkg/apis/storage/types.go``;
    consumed by the PV controller's provisioner and the DefaultStorageClass
    admission plugin)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    provisioner: str = ""  # "" = no dynamic provisioning for this class
    reclaim_policy: str = "Delete"
    parameters: dict = field(default_factory=dict)
    is_default: bool = False  # reference: the is-default-class annotation

    KIND = "StorageClass"

    def __post_init__(self):
        self.meta.namespace = ""

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "provisioner": self.provisioner,
            "reclaimPolicy": self.reclaim_policy,
            "parameters": dict(self.parameters),
            "isDefault": self.is_default,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "StorageClass":
        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        meta.namespace = ""
        return cls(
            meta=meta,
            provisioner=d.get("provisioner", ""),
            reclaim_policy=d.get("reclaimPolicy", "Delete"),
            parameters=dict(d.get("parameters") or {}),
            is_default=bool(d.get("isDefault")),
        )


@_register_cluster_scoped
@dataclass
class APIService:
    """Aggregation registration (reference ``kube-aggregator``
    ``apiregistration.k8s.io/APIService``): requests under
    ``/apis/<group>/...`` proxy to the named backend server.  The
    reference resolves a Service reference; ``url`` carries the resolved
    backend directly (the proxy handshake, availability condition, and
    route installation are the capability)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    group: str = ""
    url: str = ""  # backend base URL, e.g. http://127.0.0.1:9443
    available: bool = False  # status condition, set by the aggregator probe

    KIND = "APIService"

    def __post_init__(self):
        self.meta.namespace = ""

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {"group": self.group, "url": self.url},
            "status": {"available": self.available},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "APIService":
        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        meta.namespace = ""
        spec = d.get("spec") or {}
        return cls(
            meta=meta,
            group=spec.get("group", ""),
            url=spec.get("url", ""),
            available=bool((d.get("status") or {}).get("available")),
        )


@_register_cluster_scoped
@dataclass
class PriorityClass:
    """Named pod priority (reference ``pkg/apis/scheduling/types.go``;
    resolved into ``pod.spec.priority`` by the Priority admission plugin)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    value: int = 0
    global_default: bool = False
    description: str = ""

    KIND = "PriorityClass"

    def __post_init__(self):
        self.meta.namespace = ""

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "value": self.value,
            "globalDefault": self.global_default,
            "description": self.description,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PriorityClass":
        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        meta.namespace = ""
        return cls(
            meta=meta,
            value=int(d.get("value", 0)),
            global_default=bool(d.get("globalDefault", False)),
            description=d.get("description", ""),
        )


@_register_cluster_scoped
@dataclass
class CertificateSigningRequest:
    """CSR (reference ``pkg/apis/certificates/types.go``; signed/approved by
    ``pkg/controller/certificates``)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    request: str = ""  # opaque CSR payload
    username: str = ""
    usages: list[str] = field(default_factory=list)
    conditions: list[dict] = field(default_factory=list)  # Approved | Denied
    certificate: str = ""  # issued cert payload

    KIND = "CertificateSigningRequest"

    def __post_init__(self):
        self.meta.namespace = ""

    @property
    def approved(self) -> bool:
        return any(c.get("type") == "Approved" for c in self.conditions)

    @property
    def denied(self) -> bool:
        return any(c.get("type") == "Denied" for c in self.conditions)

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "request": self.request,
                "username": self.username,
                "usages": list(self.usages),
            },
            "status": {
                "conditions": list(self.conditions),
                "certificate": self.certificate,
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CertificateSigningRequest":
        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        meta.namespace = ""
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return cls(
            meta=meta,
            request=spec.get("request", ""),
            username=spec.get("username", ""),
            usages=list(spec.get("usages") or []),
            conditions=list(status.get("conditions") or []),
            certificate=status.get("certificate", ""),
        )


@dataclass
class PodSecurityPolicy:
    """Cluster-scoped pod security policy (reference
    ``pkg/apis/extensions`` PodSecurityPolicy; admission at
    ``plugin/pkg/admission/security/podsecuritypolicy``): what a pod may
    request — privilege, host namespaces, user ranges, volume kinds."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    privileged: bool = False
    host_pid: bool = False
    host_ipc: bool = False
    host_network: bool = False
    # {"rule": "RunAsAny"} or {"rule": "MustRunAs", "min": N, "max": M}
    run_as_user: dict = field(default_factory=lambda: {"rule": "RunAsAny"})
    # volume disk kinds a pod may mount; ["*"] = all
    allowed_volume_kinds: list = field(default_factory=lambda: ["*"])

    KIND = "PodSecurityPolicy"

    def __post_init__(self):
        self.meta.namespace = ""

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "privileged": self.privileged,
                "hostPID": self.host_pid,
                "hostIPC": self.host_ipc,
                "hostNetwork": self.host_network,
                "runAsUser": dict(self.run_as_user),
                "allowedVolumeKinds": list(self.allowed_volume_kinds),
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PodSecurityPolicy":
        spec = d.get("spec") or {}
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            privileged=bool(spec.get("privileged", False)),
            host_pid=bool(spec.get("hostPID", False)),
            host_ipc=bool(spec.get("hostIPC", False)),
            host_network=bool(spec.get("hostNetwork", False)),
            run_as_user=dict(spec.get("runAsUser") or {"rule": "RunAsAny"}),
            allowed_volume_kinds=(list(spec["allowedVolumeKinds"])
                                  if spec.get("allowedVolumeKinds") is not None
                                  else ["*"]),
        )


register_kind(PodSecurityPolicy, cluster_scoped=True,
              plural="podsecuritypolicies")


@dataclass
class NetworkPolicyPort:
    """Port a rule allows traffic on (reference
    ``pkg/apis/networking/types.go:80 NetworkPolicyPort``): protocol
    defaults to TCP; port may be numeric, a named container port, or
    absent (all ports)."""

    protocol: str = "TCP"
    port: Optional[object] = None  # int | str (named) | None = all

    def to_dict(self) -> dict:
        d: dict = {"protocol": self.protocol}
        if self.port is not None:
            d["port"] = self.port
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "NetworkPolicyPort":
        return cls(protocol=d.get("protocol", "TCP"), port=d.get("port"))


@dataclass
class NetworkPolicyPeer:
    """Traffic source (``types.go:94 NetworkPolicyPeer``): exactly one of
    podSelector (same namespace) or namespaceSelector."""

    pod_selector: Optional[LabelSelector] = None
    namespace_selector: Optional[LabelSelector] = None

    def to_dict(self) -> dict:
        d: dict = {}
        if self.pod_selector is not None:
            d["podSelector"] = self.pod_selector.to_dict()
        if self.namespace_selector is not None:
            d["namespaceSelector"] = self.namespace_selector.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "NetworkPolicyPeer":
        return cls(
            pod_selector=(LabelSelector.from_dict(d["podSelector"])
                          if "podSelector" in d else None),
            namespace_selector=(LabelSelector.from_dict(d["namespaceSelector"])
                                if "namespaceSelector" in d else None),
        )


@dataclass
class NetworkPolicyIngressRule:
    """One allowed-traffic rule (``types.go:60``): empty ports = all
    ports; empty from = all sources; a rule matches ports AND from."""

    ports: list = field(default_factory=list)   # [NetworkPolicyPort]
    from_peers: list = field(default_factory=list)  # [NetworkPolicyPeer]

    def to_dict(self) -> dict:
        d: dict = {}
        if self.ports:
            d["ports"] = [p.to_dict() for p in self.ports]
        if self.from_peers:
            d["from"] = [p.to_dict() for p in self.from_peers]
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "NetworkPolicyIngressRule":
        return cls(
            ports=[NetworkPolicyPort.from_dict(x) for x in d.get("ports") or []],
            from_peers=[NetworkPolicyPeer.from_dict(x) for x in d.get("from") or []],
        )


@dataclass
class NetworkPolicy:
    """Pod-traffic isolation policy (reference
    ``pkg/apis/networking/types.go:29``; REST storage
    ``pkg/registry/networking/networkpolicy``).  Like the reference era,
    the API object is the contract — enforcement was CNI-plugin-side
    there and is the kubenet layer's concern here; selection semantics
    (podSelector picks the isolated pods; ingress rules are additive
    across policies; a selected pod with zero rules accepts nothing)
    are what the type carries."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    pod_selector: LabelSelector = field(default_factory=LabelSelector)
    ingress: list = field(default_factory=list)  # [NetworkPolicyIngressRule]

    KIND = "NetworkPolicy"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "podSelector": self.pod_selector.to_dict(),
                "ingress": [r.to_dict() for r in self.ingress],
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "NetworkPolicy":
        spec = d.get("spec") or {}
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            pod_selector=LabelSelector.from_dict(spec.get("podSelector")),
            ingress=[NetworkPolicyIngressRule.from_dict(x)
                     for x in spec.get("ingress") or []],
        )

    # -- selection semantics (consumed by kubenet / tests) ----------------
    def selects(self, pod) -> bool:
        return self.pod_selector.matches(pod.meta.labels)

    def allows(self, from_pod, from_namespace_labels: dict,
               to_port: Optional[int] = None,
               to_port_name: str = "",
               protocol: str = "TCP") -> bool:
        """Does any ingress rule admit ``protocol`` traffic from
        ``from_pod``?  (``from_namespace_labels``: labels of the source
        namespace.)  A podSelector peer only selects pods in the
        policy's OWN namespace — cross-namespace sources must match a
        namespaceSelector peer."""
        for rule in self.ingress:
            if rule.ports:
                port_ok = any(
                    p.protocol == protocol
                    and ((p.port is None)
                         or (isinstance(p.port, int) and p.port == to_port)
                         or (isinstance(p.port, str) and p.port == to_port_name))
                    for p in rule.ports)
                if not port_ok:
                    continue
            if not rule.from_peers:
                return True
            for peer in rule.from_peers:
                if peer.pod_selector is not None:
                    if (from_pod.meta.namespace == self.meta.namespace
                            and peer.pod_selector.matches(from_pod.meta.labels)):
                        return True
                elif peer.namespace_selector is not None:
                    if peer.namespace_selector.matches(from_namespace_labels):
                        return True
        return False


register_kind(NetworkPolicy, plural="networkpolicies")
