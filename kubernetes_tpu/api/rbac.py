"""RBAC policy API types (reference ``pkg/apis/rbac/types.go``:
PolicyRule :47, Role :103, RoleBinding :118, ClusterRole :135,
ClusterRoleBinding :150; evaluated by
``plugin/pkg/auth/authorizer/rbac/rbac.go``)."""

from __future__ import annotations

from dataclasses import dataclass, field

from .meta import ObjectMeta
from .types import register_cluster_scoped as _register_cluster_scoped, register_kind

ALL = "*"  # matches any verb/resource/name (reference rbac.APIGroupAll etc.)


@dataclass
class PolicyRule:
    verbs: list[str] = field(default_factory=list)
    resources: list[str] = field(default_factory=list)
    resource_names: list[str] = field(default_factory=list)

    def matches(self, verb: str, resource: str, name: str = "") -> bool:
        """Reference ``rbac.go RuleAllows`` semantics."""
        if ALL not in self.verbs and verb not in self.verbs:
            return False
        if ALL not in self.resources and resource not in self.resources:
            return False
        if self.resource_names and name not in self.resource_names:
            return False
        return True

    def to_dict(self) -> dict:
        return {
            "verbs": list(self.verbs),
            "resources": list(self.resources),
            "resourceNames": list(self.resource_names),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PolicyRule":
        return cls(
            verbs=list(d.get("verbs") or []),
            resources=list(d.get("resources") or []),
            resource_names=list(d.get("resourceNames") or []),
        )


@dataclass
class Subject:
    kind: str = "User"  # User | Group | ServiceAccount
    name: str = ""
    namespace: str = ""

    def to_dict(self) -> dict:
        return {"kind": self.kind, "name": self.name, "namespace": self.namespace}

    @classmethod
    def from_dict(cls, d: dict) -> "Subject":
        return cls(
            kind=d.get("kind", "User"),
            name=d.get("name", ""),
            namespace=d.get("namespace", ""),
        )


def _role_to_dict(self) -> dict:
    return {
        "kind": self.KIND,
        "metadata": self.meta.to_dict(),
        "rules": [r.to_dict() for r in self.rules],
    }


@register_kind
@dataclass
class Role:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    rules: list[PolicyRule] = field(default_factory=list)

    KIND = "Role"
    to_dict = _role_to_dict

    @classmethod
    def from_dict(cls, d: dict) -> "Role":
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            rules=[PolicyRule.from_dict(r) for r in d.get("rules") or []],
        )


@_register_cluster_scoped
@dataclass
class ClusterRole:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    rules: list[PolicyRule] = field(default_factory=list)

    KIND = "ClusterRole"
    to_dict = _role_to_dict

    def __post_init__(self):
        self.meta.namespace = ""

    @classmethod
    def from_dict(cls, d: dict) -> "ClusterRole":
        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        meta.namespace = ""
        return cls(
            meta=meta,
            rules=[PolicyRule.from_dict(r) for r in d.get("rules") or []],
        )


def _binding_to_dict(self) -> dict:
    return {
        "kind": self.KIND,
        "metadata": self.meta.to_dict(),
        "subjects": [s.to_dict() for s in self.subjects],
        "roleRef": {"kind": self.role_kind, "name": self.role_name},
    }


@register_kind
@dataclass
class RoleBinding:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    subjects: list[Subject] = field(default_factory=list)
    role_kind: str = "Role"  # Role | ClusterRole
    role_name: str = ""

    KIND = "RoleBinding"
    to_dict = _binding_to_dict

    @classmethod
    def from_dict(cls, d: dict) -> "RoleBinding":
        ref = d.get("roleRef") or {}
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            subjects=[Subject.from_dict(s) for s in d.get("subjects") or []],
            role_kind=ref.get("kind", "Role"),
            role_name=ref.get("name", ""),
        )


@_register_cluster_scoped
@dataclass
class ClusterRoleBinding:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    subjects: list[Subject] = field(default_factory=list)
    role_kind: str = "ClusterRole"
    role_name: str = ""

    KIND = "ClusterRoleBinding"
    to_dict = _binding_to_dict

    def __post_init__(self):
        self.meta.namespace = ""

    @classmethod
    def from_dict(cls, d: dict) -> "ClusterRoleBinding":
        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        meta.namespace = ""
        ref = d.get("roleRef") or {}
        return cls(
            meta=meta,
            subjects=[Subject.from_dict(s) for s in d.get("subjects") or []],
            role_kind=ref.get("kind", "ClusterRole"),
            role_name=ref.get("name", ""),
        )
