"""Binary wire format: the protobuf-equivalent serialization.

The reference stores protobuf in etcd and negotiates
``application/vnd.kubernetes.protobuf`` between clients and the
apiserver (``runtime/serializer/protobuf``; stored values carry a 4-byte
magic prefix).  This codec fills the same role for this control plane's
wire objects (the dict form every kind round-trips through): a compact
tag/length/value encoding with an interned key table, so a LIST of 10k
pods doesn't repeat ``"metadata"`` ten thousand times the way JSON does.

Layout:
    MAGIC (4 bytes) | key-table | root value
    key-table  = varint count, then count x (varint len | utf8)
    value      = 1 type byte, then payload
        0 null | 1 true | 2 false
        3 int     zigzag varint
        4 float   8-byte IEEE754 big-endian
        5 str     varint len | utf8
        7 list    varint count | values
        8 dict    varint count | (varint key-id | value) pairs
        9 str-interned  varint key-id   (repeated string values)

Content negotiation: ``application/vnd.ktpu.binary`` as Content-Type
(request bodies) and Accept (responses) on the wire server; RemoteStore
opts in with ``binary=True``.
"""

from __future__ import annotations

import struct

MAGIC = b"ktpu"
CONTENT_TYPE = "application/vnd.ktpu.binary"


def _write_varint(out: bytearray, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    shift = 0
    result = 0
    while True:
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _zigzag(n: int) -> int:
    return n << 1 if n >= 0 else ((-n) << 1) - 1


def _unzigzag(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


class _Encoder:
    def __init__(self):
        self.keys: dict[str, int] = {}
        self.body = bytearray()
        self._seen_long: set[str] = set()

    def _key_id(self, key: str) -> int:
        kid = self.keys.get(key)
        if kid is None:
            kid = len(self.keys)
            self.keys[key] = kid
        return kid

    def value(self, v) -> None:
        out = self.body
        if v is None:
            out.append(0)
        elif v is True:
            out.append(1)
        elif v is False:
            out.append(2)
        elif isinstance(v, int):
            out.append(3)
            _write_varint(out, _zigzag(v))
        elif isinstance(v, float):
            out.append(4)
            out += struct.pack(">d", v)
        elif isinstance(v, str):
            # intern repeated strings (label values, phases, kinds): the
            # second occurrence costs 1-3 bytes.  Short strings intern
            # eagerly; long ones (image digests, cert blobs) from their
            # SECOND occurrence, so a unique long string isn't stored
            # twice (inline + table)
            if v in self.keys or (v and (len(v) < 64 or v in self._seen_long)):
                out.append(9)
                _write_varint(out, self._key_id(v))
            else:
                if v:
                    self._seen_long.add(v)
                data = v.encode()
                out.append(5)
                _write_varint(out, len(data))
                out += data
        elif isinstance(v, list):
            out.append(7)
            _write_varint(out, len(v))
            for item in v:
                self.value(item)
        elif isinstance(v, dict):
            out.append(8)
            _write_varint(out, len(v))
            for k, item in v.items():
                _write_varint(out, self._key_id(str(k)))
                self.value(item)
        else:
            # Quantity and friends serialize through their json form
            to_json = getattr(v, "to_json", None)
            if to_json is not None:
                self.value(to_json())
            else:
                raise TypeError(f"unencodable type {type(v)!r}")


def encode(obj) -> bytes:
    enc = _Encoder()
    enc.value(obj)
    table = bytearray()
    _write_varint(table, len(enc.keys))
    for key in enc.keys:  # dicts preserve insertion order = id order
        data = key.encode()
        _write_varint(table, len(data))
        table += data
    return MAGIC + bytes(table) + bytes(enc.body)


def decode(data: bytes):
    if data[:4] != MAGIC:
        raise ValueError("bad magic: not ktpu binary wire data")
    count, pos = _read_varint(data, 4)
    keys: list[str] = []
    for _ in range(count):
        ln, pos = _read_varint(data, pos)
        keys.append(data[pos:pos + ln].decode())
        pos += ln

    def read(pos: int):
        t = data[pos]
        pos += 1
        if t == 0:
            return None, pos
        if t == 1:
            return True, pos
        if t == 2:
            return False, pos
        if t == 3:
            n, pos = _read_varint(data, pos)
            return _unzigzag(n), pos
        if t == 4:
            return struct.unpack(">d", data[pos:pos + 8])[0], pos + 8
        if t == 5:
            ln, pos = _read_varint(data, pos)
            return data[pos:pos + ln].decode(), pos + ln
        if t == 7:
            n, pos = _read_varint(data, pos)
            out = []
            for _ in range(n):
                v, pos = read(pos)
                out.append(v)
            return out, pos
        if t == 8:
            n, pos = _read_varint(data, pos)
            d = {}
            for _ in range(n):
                kid, pos = _read_varint(data, pos)
                v, pos = read(pos)
                d[keys[kid]] = v
            return d, pos
        if t == 9:
            kid, pos = _read_varint(data, pos)
            return keys[kid], pos
        raise ValueError(f"bad type tag {t} at {pos - 1}")

    value, _ = read(pos)
    return value
