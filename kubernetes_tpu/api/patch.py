"""Patch application: the three patch types of the reference API
(``endpoints/handlers/rest.go`` PATCH → strategic-merge / merge /
JSON-patch).  Shared by the server-side PATCH verb and kubectl patch."""

from __future__ import annotations

MERGE = "merge"
STRATEGIC = "strategic"
JSON_PATCH = "json"

CONTENT_TYPES = {
    "application/merge-patch+json": MERGE,
    "application/strategic-merge-patch+json": STRATEGIC,
    "application/json-patch+json": JSON_PATCH,
}


def merge_patch(base, overlay, strategic: bool = False):
    """RFC 7386 recursive merge (null deletes); with ``strategic``, lists
    whose members all carry a "name" key merge by name (the reference's
    patchMergeKey for containers/ports/env/volumes) instead of replacing
    wholesale."""
    if (strategic and isinstance(base, list) and isinstance(overlay, list)
            and all(isinstance(x, dict) and "name" in x for x in base + overlay)):
        out_list = list(base)
        index = {x["name"]: i for i, x in enumerate(out_list)}
        for item in overlay:
            i = index.get(item["name"])
            if i is None:
                out_list.append(item)
            else:
                out_list[i] = merge_patch(out_list[i], item, strategic)
        return out_list
    if not isinstance(base, dict) or not isinstance(overlay, dict):
        return overlay
    out = dict(base)
    for k, v in overlay.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = merge_patch(out.get(k), v, strategic)
    return out


def json_patch(base, ops):
    """RFC 6902 add/replace/remove with simple paths (the subset the
    reference's callers actually use)."""
    for op in ops:
        path = [p for p in op.get("path", "").split("/") if p]
        target = base
        for seg in path[:-1]:
            target = target[int(seg)] if isinstance(target, list) else target[seg]
        leaf = path[-1] if path else ""
        action = op.get("op")
        if isinstance(target, list):
            idx = len(target) if leaf == "-" else int(leaf)
            if action == "add":
                target.insert(idx, op.get("value"))
            elif action == "replace":
                target[idx] = op.get("value")
            elif action == "remove":
                del target[idx]
            else:
                raise ValueError(f"unsupported op {action!r}")
        else:
            if action in ("add", "replace"):
                target[leaf] = op.get("value")
            elif action == "remove":
                del target[leaf]
            else:
                raise ValueError(f"unsupported op {action!r}")
    return base


def apply_patch(current: dict, patch, patch_type: str) -> dict:
    if patch_type == JSON_PATCH:
        return json_patch(current, patch)
    return merge_patch(current, patch, strategic=patch_type == STRATEGIC)
