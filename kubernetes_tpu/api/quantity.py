"""Resource quantity arithmetic.

Equivalent capability to the reference's ``resource.Quantity``
(``staging/src/k8s.io/apimachinery/pkg/api/resource``): exact arithmetic on
resource amounts written with SI-decimal ("100m", "250M", "1.5k") or
binary ("128Mi", "2Gi") suffixes, plain integers, and scientific notation.

Design difference from the reference (TPU-first): rather than an
arbitrary-precision decimal kept through the whole scheduler, quantities are
parsed **once at the API boundary** into exact :class:`fractions.Fraction`
values and then *canonicalized to fixed-point int32 units* for all scheduling
math (see :mod:`kubernetes_tpu.scheduler.units`).  int32 is what both the CPU
oracle and the TPU VPU compute in, which is what makes oracle-vs-TPU score
parity exact instead of "close".
"""

from __future__ import annotations

import re
from fractions import Fraction
from functools import lru_cache
from functools import total_ordering

_BINARY_SUFFIXES = {
    "Ki": 2**10,
    "Mi": 2**20,
    "Gi": 2**30,
    "Ti": 2**40,
    "Pi": 2**50,
    "Ei": 2**60,
}
_DECIMAL_SUFFIXES = {
    "n": Fraction(1, 10**9),
    "u": Fraction(1, 10**6),
    "m": Fraction(1, 10**3),
    "": Fraction(1),
    "k": 10**3,
    "M": 10**6,
    "G": 10**9,
    "T": 10**12,
    "P": 10**15,
    "E": 10**18,
}

_QUANTITY_RE = re.compile(
    r"^(?P<sign>[+-]?)(?P<num>[0-9]+(?:\.[0-9]*)?|\.[0-9]+)"
    r"(?:[eE](?P<exp>[+-]?[0-9]+))?"
    r"(?P<suffix>Ki|Mi|Gi|Ti|Pi|Ei|[numkMGTPE]?)$"
)


@total_ordering
class Quantity:
    """An exact resource amount.

    Internally a :class:`fractions.Fraction`; all comparisons and arithmetic
    are exact.  ``value()`` / ``milli_value()`` round *up* like the
    reference's ``Quantity.Value()`` so that "0.5" of anything never
    under-reserves.
    """

    __slots__ = ("_frac", "_orig")

    def __init__(self, value: "Quantity | Fraction | int | float | str" = 0):
        if isinstance(value, Quantity):
            self._frac = value._frac
            self._orig = value._orig
        elif isinstance(value, str):
            self._frac = _parse(value)
            self._orig = value
        elif isinstance(value, (int, Fraction)):
            self._frac = Fraction(value)
            self._orig = None
        elif isinstance(value, float):
            # floats arrive from JSON numbers; snap to a sane decimal.
            self._frac = Fraction(str(value))
            self._orig = None
        else:
            raise TypeError(f"cannot make Quantity from {type(value)!r}")

    # -- accessors ---------------------------------------------------------
    @property
    def fraction(self) -> Fraction:
        return self._frac

    def value(self) -> int:
        """Integer value, rounded away from zero (ceil for positives)."""
        f = self._frac
        q, r = divmod(f.numerator, f.denominator)
        if r != 0 and f > 0:
            q += 1
        return q

    def milli_value(self) -> int:
        """Value in thousandths, rounded away from zero."""
        f = self._frac * 1000
        q, r = divmod(f.numerator, f.denominator)
        if r != 0 and f > 0:
            q += 1
        return q

    def is_zero(self) -> bool:
        return self._frac == 0

    # -- arithmetic --------------------------------------------------------
    def __add__(self, other: "Quantity | int") -> "Quantity":
        return Quantity(self._frac + _coerce(other))

    def __sub__(self, other: "Quantity | int") -> "Quantity":
        return Quantity(self._frac - _coerce(other))

    def __neg__(self) -> "Quantity":
        return Quantity(-self._frac)

    def __eq__(self, other) -> bool:
        try:
            return self._frac == _coerce(other)
        except TypeError:
            return NotImplemented

    def __lt__(self, other) -> bool:
        return self._frac < _coerce(other)

    def __hash__(self) -> int:
        return hash(self._frac)

    # -- serialization -----------------------------------------------------
    def __str__(self) -> str:
        if self._orig is not None:
            return self._orig
        f = self._frac
        if f.denominator == 1:
            return str(f.numerator)
        m = f * 1000
        if m.denominator == 1:
            return f"{m.numerator}m"
        # fall back to decimal with enough digits; exactness already kept
        return str(float(f))

    def __repr__(self) -> str:
        return f"Quantity({str(self)!r})"

    def to_json(self) -> str:
        return str(self)

    @classmethod
    def from_json(cls, v) -> "Quantity":
        if isinstance(v, (int, float, str)):
            return cls(v)
        raise TypeError(f"bad quantity json: {v!r}")


def _coerce(v) -> Fraction:
    if isinstance(v, Quantity):
        return v._frac
    if isinstance(v, (int, Fraction)):
        return Fraction(v)
    if isinstance(v, str):
        return _parse(v)
    raise TypeError(f"cannot compare Quantity with {type(v)!r}")


@lru_cache(maxsize=8192)
def _parse(s: str) -> Fraction:
    """Memoized: clusters reuse a handful of quantity strings ("100m",
    "128Mi", …) across hundreds of thousands of objects, and Fractions are
    immutable so sharing is safe."""
    s = s.strip()
    m = _QUANTITY_RE.match(s)
    if not m:
        raise ValueError(f"invalid quantity {s!r}")
    num = Fraction(m.group("num"))
    if m.group("exp"):
        exp = int(m.group("exp"))
        num *= Fraction(10) ** exp
    suffix = m.group("suffix")
    if suffix in _BINARY_SUFFIXES:
        num *= _BINARY_SUFFIXES[suffix]
    else:
        num *= _DECIMAL_SUFFIXES[suffix]
    if m.group("sign") == "-":
        num = -num
    return num


def parse_quantity(s) -> Quantity:
    return Quantity(s)
