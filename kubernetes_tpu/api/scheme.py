"""Hub-and-spoke versioning: wire versions convert through internal types.

Capability of the reference's ``runtime.Scheme``
(``apimachinery/pkg/runtime/scheme.go``, 569 lines): each kind has ONE
internal (hub) schema — this framework's dataclasses — plus N versioned
wire schemas (spokes) with conversion + defaulting at the boundary, so
old manifests keep working as APIs evolve.  The registered spokes here
are the reference era's own wire shapes, which means **actual
Kubernetes v1.7 YAML applies unchanged**:

- ``apps/v1beta1`` / ``extensions/v1beta1`` Deployment — nested
  ``spec.strategy.rollingUpdate.{maxSurge,maxUnavailable}`` (the
  internal hub flattens them), selector defaulted from template labels;
- ``batch/v1`` Job, ``batch/v2alpha1`` CronJob — ``spec.jobTemplate``
  nesting;
- ``v1`` core kinds — already the hub wire form (identity spoke).

``convert_to_internal(doc)`` is the decode path (kubectl create/apply,
the apiserver's create handler); ``convert_from_internal(doc, gv)``
re-encodes for clients that ask for a specific wire version."""

from __future__ import annotations

import copy
from typing import Callable, Optional

# (group/version, kind) -> decoder(wire dict) -> internal dict
_DECODERS: dict[tuple[str, str], Callable[[dict], dict]] = {}
# (group/version, kind) -> encoder(internal dict) -> wire dict
_ENCODERS: dict[tuple[str, str], Callable[[dict], dict]] = {}


def register_conversion(gv: str, kind: str,
                        decoder: Callable[[dict], dict],
                        encoder: Optional[Callable[[dict], dict]] = None) -> None:
    _DECODERS[(gv, kind)] = decoder
    if encoder is not None:
        _ENCODERS[(gv, kind)] = encoder


def convert_to_internal(doc: dict) -> dict:
    """Decode a wire document: versioned spokes convert; unversioned or
    hub-form documents pass through (with apiVersion stripped so the
    store holds exactly one schema)."""
    doc = copy.deepcopy(doc)
    gv = doc.pop("apiVersion", "")
    kind = doc.get("kind", "")
    dec = _DECODERS.get((gv, kind))
    if dec is not None:
        return dec(doc)
    return doc


def convert_from_internal(doc: dict, gv: str) -> dict:
    kind = doc.get("kind", "")
    enc = _ENCODERS.get((gv, kind))
    out = enc(copy.deepcopy(doc)) if enc is not None else copy.deepcopy(doc)
    out["apiVersion"] = gv
    return out


# -- Deployment: apps/v1beta1 & extensions/v1beta1 --------------------------
# reference wire (staging/src/k8s.io/api/apps/v1beta1/types.go):
#   spec.strategy: {type, rollingUpdate: {maxSurge, maxUnavailable}}
#   spec.selector may be omitted -> defaulted from template labels
#   (defaults in pkg/apis/apps/v1beta1/defaults.go)


def _intstr(v, total: int, round_up: bool) -> int:
    """The era's IntOrString on surge/unavailable: ints pass through;
    percentages resolve against replicas with the reference's rounding —
    maxSurge rounds UP, maxUnavailable rounds DOWN (so "5%" of 10 is 1
    surge but 0 unavailable; ``deployment/util.ResolveFenceposts``).

    Documented divergence: the reference re-resolves percentages against
    the CURRENT replica count on every rollout; the hub schema stores
    absolute ints, so percentages resolve once at decode time — a later
    rescale keeps the decoded absolutes."""
    if isinstance(v, int):
        return v
    if isinstance(v, str) and v.endswith("%"):
        pct = int(v[:-1])
        n = pct * max(total, 1)
        return -(-n // 100) if round_up else n // 100
    return int(v)


def _deployment_v1beta1_decode(doc: dict) -> dict:
    spec = doc.setdefault("spec", {})
    strategy = spec.pop("strategy", None) or {}
    stype = strategy.get("type", "RollingUpdate")
    spec["strategy"] = stype
    replicas = int(spec.get("replicas", 1))
    if stype == "RollingUpdate":
        ru = strategy.get("rollingUpdate") or {}
        # era defaults: maxSurge=1, maxUnavailable=1
        spec["maxSurge"] = _intstr(ru.get("maxSurge", 1), replicas, round_up=True)
        spec["maxUnavailable"] = _intstr(ru.get("maxUnavailable", 1), replicas, round_up=False)
    if not spec.get("selector"):
        # defaulting: selector <- template labels (defaults.go)
        labels = ((spec.get("template") or {}).get("metadata") or {}).get("labels") or {}
        spec["selector"] = {"matchLabels": dict(labels)}
    return doc


def _deployment_v1beta1_encode(doc: dict) -> dict:
    spec = doc.setdefault("spec", {})
    stype = spec.pop("strategy", "RollingUpdate")
    surge = spec.pop("maxSurge", 1)
    unavail = spec.pop("maxUnavailable", 0)
    strategy = {"type": stype}
    if stype == "RollingUpdate":
        strategy["rollingUpdate"] = {"maxSurge": surge, "maxUnavailable": unavail}
    spec["strategy"] = strategy
    return doc


for _gv in ("apps/v1beta1", "extensions/v1beta1"):
    register_conversion(_gv, "Deployment",
                        _deployment_v1beta1_decode, _deployment_v1beta1_encode)


# -- CronJob: batch/v2alpha1 (the era's group) -------------------------------
# wire: spec.jobTemplate.spec is the Job spec; internal flattens to the
# CronJob's own job fields


def _cronjob_v2alpha1_decode(doc: dict) -> dict:
    spec = doc.setdefault("spec", {})
    jt = spec.get("jobTemplate")
    if jt is not None and "spec" in jt:
        # internal hub keeps spec.jobTemplate = the Job SPEC itself
        spec["jobTemplate"] = jt.get("spec") or {}
    return doc


register_conversion("batch/v2alpha1", "CronJob", _cronjob_v2alpha1_decode)


# v1 core kinds, extensions/v1beta1 ReplicaSet/DaemonSet, and batch/v1 Job
# need no registration: the hub IS their wire form, and unregistered
# (group/version, kind) pairs pass through convert_* unchanged.
