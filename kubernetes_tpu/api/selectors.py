"""Label selectors and node-selector terms.

Capability parity with the reference's
``apimachinery/pkg/labels`` + ``apimachinery/pkg/selection`` (matchLabels /
matchExpressions with In, NotIn, Exists, DoesNotExist, Gt, Lt) and the
node-affinity ``NodeSelector`` structure used by
``PodMatchNodeSelector`` (``plugin/pkg/scheduler/algorithm/predicates/
predicates.go:686``).

TPU consequence: a selector is host-side logic over string maps; the
tensorization layer (``kubernetes_tpu/models``) evaluates each selector
against each node/pod *once on host* to produce dense boolean matrices, so
the device kernels never see strings.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Mapping, Optional

IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"


@dataclass
class Requirement:
    key: str
    operator: str
    values: list[str] = field(default_factory=list)

    def matches(self, labels: Mapping[str, str]) -> bool:
        has = self.key in labels
        if self.operator == IN:
            return has and labels[self.key] in self.values
        if self.operator == NOT_IN:
            # reference semantics (labels.Requirement.Matches): a missing key
            # satisfies NotIn.
            return not has or labels[self.key] not in self.values
        if self.operator == EXISTS:
            return has
        if self.operator == DOES_NOT_EXIST:
            return not has
        if self.operator in (GT, LT):
            if not has or len(self.values) != 1:
                return False
            try:
                lhs = int(labels[self.key])
                rhs = int(self.values[0])
            except ValueError:
                return False
            return lhs > rhs if self.operator == GT else lhs < rhs
        raise ValueError(f"unknown operator {self.operator!r}")

    def to_dict(self) -> dict:
        return {"key": self.key, "operator": self.operator, "values": list(self.values)}

    @classmethod
    def from_dict(cls, d: dict) -> "Requirement":
        return cls(d["key"], d["operator"], list(d.get("values") or []))


@dataclass
class LabelSelector:
    """matchLabels AND matchExpressions (both must hold), like
    ``metav1.LabelSelector``.  An empty selector matches everything; a None
    selector (where the API allows it) matches nothing — callers handle None.
    """

    match_labels: dict[str, str] = field(default_factory=dict)
    match_expressions: list[Requirement] = field(default_factory=list)

    def matches(self, labels: Mapping[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        return all(r.matches(labels) for r in self.match_expressions)

    def is_empty(self) -> bool:
        return not self.match_labels and not self.match_expressions

    def to_dict(self) -> dict:
        d: dict = {}
        if self.match_labels:
            d["matchLabels"] = dict(self.match_labels)
        if self.match_expressions:
            d["matchExpressions"] = [r.to_dict() for r in self.match_expressions]
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "LabelSelector":
        d = d or {}
        return cls(
            match_labels=dict(d.get("matchLabels") or {}),
            match_expressions=[
                Requirement.from_dict(r) for r in d.get("matchExpressions") or []
            ],
        )

    @classmethod
    def from_match_labels(cls, labels: Mapping[str, str]) -> "LabelSelector":
        return cls(match_labels=dict(labels))


@dataclass
class NodeSelectorTerm:
    """One term of a NodeSelector: ANDed matchExpressions over node labels."""

    match_expressions: list[Requirement] = field(default_factory=list)

    def matches(self, labels: Mapping[str, str]) -> bool:
        # reference: a term with no expressions matches nothing
        # (v1/helper nodeSelectorRequirementsAsSelector returns nil selector).
        if not self.match_expressions:
            return False
        return all(r.matches(labels) for r in self.match_expressions)

    def to_dict(self) -> dict:
        return {"matchExpressions": [r.to_dict() for r in self.match_expressions]}

    @classmethod
    def from_dict(cls, d: dict) -> "NodeSelectorTerm":
        return cls([Requirement.from_dict(r) for r in d.get("matchExpressions") or []])


@dataclass
class NodeSelector:
    """ORed list of terms (``v1.NodeSelector``): node matches if ANY term
    matches — reference ``pkg/api/v1/helper.MatchNodeSelectorTerms``."""

    terms: list[NodeSelectorTerm] = field(default_factory=list)

    def matches(self, labels: Mapping[str, str]) -> bool:
        return any(t.matches(labels) for t in self.terms)

    def to_dict(self) -> dict:
        return {"nodeSelectorTerms": [t.to_dict() for t in self.terms]}

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "Optional[NodeSelector]":
        if d is None:
            return None
        return cls([NodeSelectorTerm.from_dict(t) for t in d.get("nodeSelectorTerms") or []])


def matches_simple_selector(selector: Mapping[str, str], labels: Mapping[str, str]) -> bool:
    """Plain map-equality selector (pod.spec.nodeSelector, service.spec.selector)."""
    return all(labels.get(k) == v for k, v in selector.items())


def parse_selector_string(spec: str) -> LabelSelector:
    """Parse the wire ``labelSelector=`` string grammar
    (``apimachinery labels.Parse``): ``k=v``, ``k==v``, ``k!=v``,
    ``k`` (exists), ``!k`` (not exists), ``k in (a,b)``,
    ``k notin (a,b)`` — comma separated.  Raises ValueError on garbage."""
    import re

    reqs: list[Requirement] = []
    # split on commas OUTSIDE parentheses
    parts = re.split(r",(?![^(]*\))", spec)
    for part in parts:
        part = part.strip()
        if not part:
            continue
        m = re.fullmatch(r"(\S+)\s+(in|notin)\s+\(([^)]*)\)", part)
        if m:
            values = [v.strip() for v in m.group(3).split(",") if v.strip()]
            if not values:
                raise ValueError(f"empty value set in {part!r}")
            reqs.append(Requirement(m.group(1),
                                    IN if m.group(2) == "in" else NOT_IN, values))
            continue
        if "!=" in part:
            k, v = part.split("!=", 1)
            if not k.strip():
                raise ValueError(f"empty key in {part!r}")
            reqs.append(Requirement(k.strip(), NOT_IN, [v.strip()]))
            continue
        if "==" in part or "=" in part:
            k, v = (part.split("==", 1) if "==" in part else part.split("=", 1))
            if not k.strip():
                raise ValueError(f"empty key in {part!r}")
            reqs.append(Requirement(k.strip(), IN, [v.strip()]))
            continue
        if part.startswith("!"):
            reqs.append(Requirement(part[1:].strip(), DOES_NOT_EXIST))
            continue
        if re.fullmatch(r"[A-Za-z0-9._/-]+", part):
            reqs.append(Requirement(part, EXISTS))
            continue
        raise ValueError(f"cannot parse selector clause {part!r}")
    if not reqs:
        raise ValueError("empty selector")
    return LabelSelector(match_expressions=reqs)
