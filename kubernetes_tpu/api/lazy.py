"""Decode-on-access wrappers over raw wire dicts (the zero-copy ingest core).

The informer's hottest instruction used to be ``cls.from_dict(ev.object)``
— a full typed decode of every watch/LIST payload, paid whether or not any
consumer ever reads past ``meta.key`` (ROADMAP: ~0.2-0.4s per 2k-pod wave
at 5k nodes, the largest steady-state host cost after the PR 3 pipeline).
This module replaces the eager decode with **lazy views**:

- :class:`LazyPod` / :class:`LazyNode` — *sectioned* wrappers for the two
  hot kinds: ``meta`` / ``spec`` / ``status`` decode independently on first
  touch, and inside a pod spec the four expensive list fields (containers,
  affinity, tolerations, volumes — the Quantity parses and selector object
  builds that dominate ``from_dict``) defer further, so a bind-confirmation
  event whose consumers read only ``spec.node_name`` never builds a
  Container;
- a **generic full-promotion wrapper** for every other registered kind
  (services, replicasets, PVs, CRD kinds, …): zero work at wrap time, one
  cached ``from_dict`` on the first real attribute access.

Promotion is cached and carries full ``from_dict`` semantics: once a
section is decoded the typed objects are authoritative (a consumer that
mutates a promoted object — legal only outside the informer's shared-cache
contract — sees its mutation in ``to_dict`` and everywhere else, exactly
as with an eagerly decoded object).  The raw fast-path helpers below
therefore consult the raw dict ONLY while the relevant section is still
undecoded; afterwards they defer to the typed objects.

Raw readers (``raw_host_ports``, ``raw_request_units``, signature/content
keys in ``models/snapshot``) give the scheduler's per-pod loops a column
view straight over the wire payload — the "tensorize from the columns"
half of the fast path — without pinning per-pod derived objects (the
north-preset A/B in ``units.pod_request_vec`` showed per-pod caches cost
more in GC than they save; everything here memoizes by *content*, whose
vocabulary is tiny under template-stamped churn).

``ENABLED`` is the A/B seam: ``bench.py --ab-pump`` flips it to measure
lazy vs eager ingest on the same harness; the eager arm never constructs
a lazy object, so every fast path degrades to the status quo.
"""

from __future__ import annotations

import sys
from typing import Optional

from .meta import ObjectMeta, OwnerReference
from . import types as api

# module seam for the ingest A/B (bench.py --ab-pump): False restores
# eager per-event from_dict everywhere
ENABLED = True

# decode observability (read by the scheduler's per-wave phase accounting
# and the churn bench).  Plain ints bumped on the toucher's thread: the
# counters are telemetry, and a lost increment under thread interleaving
# is acceptable where a per-promotion lock round is not.
STATS = {"promotions": 0, "sections": 0, "wrapped": 0}


def stats_snapshot() -> dict:
    return dict(STATS)


# ---------------------------------------------------------------------------
# sectioned wrappers: Pod / Node
# ---------------------------------------------------------------------------


class _section:
    """Decode-on-first-touch section.  A NON-data descriptor (no
    ``__set__``): the decoded value is installed under the attribute's
    own name in the instance dict, which shadows the descriptor — every
    later read is a C-speed instance-attribute lookup, exactly what an
    eagerly decoded object pays.  (The property version of this cost a
    Python call per access, ~6x an attribute read, on the scheduler's
    hottest per-pod reads.)  Plain assignment (mutation after promotion)
    also just lands in the instance dict and wins."""

    __slots__ = ("decode", "name")

    def __init__(self, decode):
        self.decode = decode

    def __set_name__(self, owner, name):
        self.name = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        value = self.decode(obj)
        obj.__dict__[self.name] = value
        STATS["sections"] += 1
        return value


class _LazyBase:
    """Shared plumbing: raw storage + field-equality against the base
    dataclass (the generated dataclass ``__eq__`` refuses cross-class
    comparison, and a lazy view must compare equal to its eager twin)."""

    _eq_fields: tuple = ()

    def __init__(self, raw: dict):
        self.__dict__["_lzraw"] = raw
        STATS["wrapped"] += 1

    @property
    def raw(self) -> dict:
        """The wire payload this view decodes from.  Shared-immutable:
        consumers MUST NOT mutate it (informer contract)."""
        return self.__dict__["_lzraw"]

    def __eq__(self, other):
        base = self._eq_base
        if not isinstance(other, base):
            return NotImplemented
        return all(getattr(self, f) == getattr(other, f)
                   for f in self._eq_fields)

    __hash__ = None  # matches the eq=True dataclasses being wrapped

    @classmethod
    def from_dict(cls, d: dict):
        """``type(lazy_obj).from_dict(wire)`` must keep working (the
        federation fan-out constructs member copies this way): the
        inherited classmethod would call ``cls(**fields)`` into the lazy
        ``__init__(raw)`` — delegate to the eager base decode instead."""
        return cls._eq_base.from_dict(d)


class LazyObjectMeta(_LazyBase, ObjectMeta):
    """ObjectMeta view: identity scalars (name/namespace/uid/revision —
    what ``meta.key`` and the revision fences read) decode eagerly; the
    dict/list fields (labels, annotations, owner refs, finalizers) — the
    bulk of ``ObjectMeta.from_dict`` — defer to first touch."""

    _eq_base = ObjectMeta
    _eq_fields = tuple(ObjectMeta.__dataclass_fields__)

    def __init__(self, raw: "dict | None"):
        d = raw or {}
        _LazyBase.__init__(self, d)
        self.name = d.get("name", "")
        self.namespace = d.get("namespace", "default")
        self.uid = d.get("uid", "")
        self.resource_version = int(d.get("resourceVersion", 0))
        self.creation_revision = int(d.get("creationRevision", 0))
        self.deletion_revision = d.get("deletionRevision")
        self.generation = int(d.get("generation", 0))

    labels = _section(lambda self: dict(self.raw.get("labels") or {}))
    annotations = _section(lambda self: dict(self.raw.get("annotations") or {}))
    owner_references = _section(lambda self: [
        OwnerReference.from_dict(r)
        for r in self.raw.get("ownerReferences") or []])
    finalizers = _section(lambda self: list(self.raw.get("finalizers") or []))


class LazyPodSpec(_LazyBase, api.PodSpec):
    """PodSpec view: scalars decode eagerly at construction (plain dict
    gets), the four expensive list fields defer — they are where
    ``from_dict`` burns its time (Quantity parses per container,
    selector/affinity object builds)."""

    _eq_base = api.PodSpec
    _eq_fields = tuple(api.PodSpec.__dataclass_fields__)

    def __init__(self, raw: Optional[dict]):
        d = raw or {}
        _LazyBase.__init__(self, d)
        self.node_name = d.get("nodeName", "")
        self.node_selector = dict(d.get("nodeSelector") or {})
        self.priority = int(d.get("priority", 0))
        self.priority_class_name = d.get("priorityClassName", "")
        self.scheduler_name = d.get("schedulerName", "default-scheduler")
        self.restart_policy = d.get("restartPolicy", "Always")
        self.service_account_name = d.get("serviceAccountName", "")
        self.termination_grace_period_seconds = int(
            d.get("terminationGracePeriodSeconds", 30))
        ads = d.get("activeDeadlineSeconds")
        self.active_deadline_seconds = None if ads is None else int(ads)
        self.host_pid = bool(d.get("hostPID", False))
        self.host_ipc = bool(d.get("hostIPC", False))
        self.host_network = bool(d.get("hostNetwork", False))

    containers = _section(lambda self: [
        api.Container.from_dict(c) for c in self.raw.get("containers") or []])
    affinity = _section(lambda self: api.Affinity.from_dict(
        self.raw.get("affinity")))
    tolerations = _section(lambda self: [
        api.Toleration.from_dict(t) for t in self.raw.get("tolerations") or []])
    volumes = _section(lambda self: [
        api.Volume.from_dict(v) for v in self.raw.get("volumes") or []])


# the spec fields whose decode dominates from_dict — undecoded_spec's gate
_LAZY_SPEC_FIELDS = ("containers", "affinity", "tolerations", "volumes")


class LazyPod(_LazyBase, api.Pod):
    _eq_base = api.Pod
    _eq_fields = ("meta", "spec", "status")

    meta = _section(lambda self: LazyObjectMeta(self.raw.get("metadata")))
    spec = _section(lambda self: LazyPodSpec(self.raw.get("spec")))
    status = _section(lambda self: api.PodStatus.from_dict(
        self.raw.get("status")))

    def host_ports(self) -> list[tuple[str, int]]:
        spec = self.__dict__.get("spec")
        if spec is None or "containers" not in spec.__dict__:
            raw = spec.raw if spec is not None else (self.raw.get("spec") or {})
            return raw_host_ports(raw)
        return api.Pod.host_ports(self)


class LazyNode(_LazyBase, api.Node):
    _eq_base = api.Node
    _eq_fields = ("meta", "spec", "status")

    meta = _section(lambda self: LazyObjectMeta(self.raw.get("metadata")))
    spec = _section(lambda self: api.NodeSpec.from_dict(
        self.raw.get("spec")))
    status = _section(lambda self: api.NodeStatus.from_dict(
        self.raw.get("status")))


# ---------------------------------------------------------------------------
# the generic wrapper: any registered kind
# ---------------------------------------------------------------------------

_GENERIC_CACHE: dict[type, type] = {}


class _PromoteOnRead:
    """Shadows one dataclass field of a generic lazy wrapper: dataclass
    fields with PLAIN defaults exist as class attributes, so without the
    shadow a pre-promotion read would silently return the class default
    instead of promoting (``__getattr__`` only fires on a complete
    miss).  Non-data: the promoted instance attribute wins afterwards."""

    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        obj._lz_promote()
        try:
            return obj.__dict__[self.name]
        except KeyError:
            raise AttributeError(self.name) from None


def _make_generic(cls: type) -> type:
    """Subclass ``cls`` so any field read before promotion triggers one
    cached ``from_dict`` (dataclass fields via :class:`_PromoteOnRead`,
    everything else via ``__getattr__``).  Underscored names never
    promote — they are internal memo probes (``getattr(pod, "_sig_key",
    None)`` must stay O(1) and side-effect free)."""

    def __init__(self, raw: dict):
        object.__setattr__(self, "_lzraw", raw)
        STATS["wrapped"] += 1

    def _lz_promote(self):
        d = self.__dict__
        if not d.get("_lz_done"):
            full = cls.from_dict(d["_lzraw"])
            for k, v in full.__dict__.items():
                d.setdefault(k, v)  # explicit writes win over the decode
            d["_lz_done"] = True
            STATS["promotions"] += 1

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        self._lz_promote()
        return object.__getattribute__(self, name)

    def __eq__(self, other):
        if not isinstance(other, cls):
            return NotImplemented
        self._lz_promote()
        fields = getattr(cls, "__dataclass_fields__", None)
        names = tuple(fields) if fields else tuple(self.__dict__.keys() - {
            "_lzraw", "_lz_done"})
        return all(getattr(self, f) == getattr(other, f, None) for f in names)

    ns = {
        "__init__": __init__,
        "_lz_promote": _lz_promote,
        "__getattr__": __getattr__,
        "__eq__": __eq__,
        "__hash__": None,
        # type(lazy_obj).from_dict(...) must build via the EAGER base
        # (the inherited classmethod would call cls(**fields) into the
        # lazy __init__) — the federation fan-out does exactly this
        "from_dict": classmethod(lambda _cls, d: cls.from_dict(d)),
        "raw": property(lambda self: self.__dict__["_lzraw"]),
    }
    for name in getattr(cls, "__dataclass_fields__", ()):
        # deliberately AFTER the ns dict: a dataclass field named like one
        # of our helpers (DynamicObject's own `raw` payload field) must
        # win over the wire-dict accessor — field semantics come first
        ns[name] = _PromoteOnRead(name)
    return type(f"Lazy{cls.__name__}", (cls,), ns)


def lazy_class(cls: type) -> type:
    if cls is api.Pod:
        return LazyPod
    if cls is api.Node:
        return LazyNode
    sub = _GENERIC_CACHE.get(cls)
    if sub is None:
        sub = _GENERIC_CACHE[cls] = _make_generic(cls)
    return sub


def wrap(cls: type, raw: dict):
    """One lazy view over ``raw`` behaving like ``cls.from_dict(raw)``.

    A structurally broken payload must fail HERE, not later: eager
    ``from_dict`` raises at decode time and the informer degrades to
    'stale until relist'; a lazy view that accepted garbage would poison
    the shared cache and blow up in some handler or wave instead.  The
    check is shape-level only (top sections must be dicts) — field-level
    garbage still surfaces at promotion, which is isolated per handler."""
    if not isinstance(raw, dict):
        raise TypeError(f"wire payload for {cls.__name__} is "
                        f"{type(raw).__name__}, not dict")
    for section in ("metadata", "spec", "status"):
        v = raw.get(section)
        if v is not None and not isinstance(v, dict):
            raise TypeError(f"wire payload section {section!r} is "
                            f"{type(v).__name__}, not dict")
    return lazy_class(cls)(raw)


# ---------------------------------------------------------------------------
# promote-and-drop-raw compaction (ISSUE 6 satellite; ROADMAP carried item)
# ---------------------------------------------------------------------------


def _promote_all_sections(obj, names: tuple) -> None:
    for name in names:
        getattr(obj, name)  # _section installs into the instance dict


def promote_and_drop_raw(obj) -> bool:
    """Force-promote every lazy section of ``obj`` and release its pinned
    wire dict.

    A cached lazy view keeps its whole raw payload alive for its
    lifetime — including every wire field the typed form doesn't model,
    which on real payloads is most of the bytes.  This sweep converges a
    lazy object to exactly what an eager ``from_dict`` would hold: all
    sections promoted (observable value unchanged — promotion ≡
    from_dict, pinned by test_lazy), raw references nulled so the wire
    dicts can be collected.  After the drop every raw fast-path helper
    (``undecoded_spec``/``undecoded_meta``/``pod_brief``) answers through
    the typed objects — they all gate on the raw still being present.

    Returns True when a raw payload was actually dropped (False for
    eager objects and already-compacted views)."""
    d = getattr(obj, "__dict__", None)
    if d is None or d.get("_lzraw") is None:
        return False
    if isinstance(obj, (LazyPod, LazyNode)):
        _promote_all_sections(obj, ("meta", "spec", "status"))
        meta = d["meta"]
        if isinstance(meta, LazyObjectMeta):
            _promote_all_sections(meta, ("labels", "annotations",
                                         "owner_references", "finalizers"))
            meta.__dict__["_lzraw"] = None
        spec = d["spec"]
        if isinstance(spec, LazyPodSpec):
            _promote_all_sections(spec, _LAZY_SPEC_FIELDS)
            spec.__dict__["_lzraw"] = None
        d["_lzraw"] = None
        return True
    promote = getattr(obj, "_lz_promote", None)
    if promote is None:
        return False  # not a lazy view at all
    promote()
    d["_lzraw"] = None
    return True


def _approx_bytes(o) -> int:
    """Cheap recursive size estimate for a JSON-shaped wire payload —
    the compaction sweep's freed-bytes accounting.  Same O(payload) cost
    class as the promotion walk that accompanies it."""
    if isinstance(o, dict):
        return sys.getsizeof(o) + sum(
            _approx_bytes(k) + _approx_bytes(v) for k, v in o.items())
    if isinstance(o, list):
        return sys.getsizeof(o) + sum(_approx_bytes(v) for v in o)
    return sys.getsizeof(o)


def raw_payload_size(obj) -> int:
    """Approximate bytes of the wire payload ``obj`` currently pins
    (0 for eager objects and already-compacted views).  The sectioned
    lazy wrappers' nested views alias subtrees of the same top-level
    raw dict, so the top-level payload is the whole pin."""
    d = getattr(obj, "__dict__", None)
    raw = d.get("_lzraw") if d is not None else None
    return _approx_bytes(raw) if raw is not None else 0


# ---------------------------------------------------------------------------
# raw fast-path readers (the column view)
# ---------------------------------------------------------------------------


def undecoded_spec(pod) -> Optional[dict]:
    """The raw spec dict when ``pod`` is a lazy pod whose expensive spec
    fields are still undecoded — the gate every raw fast path shares.
    Returns None for eager pods and for promoted (possibly mutated)
    sections, where the typed objects are authoritative."""
    if type(pod) is not LazyPod:
        return None
    spec = pod.__dict__.get("spec")
    if spec is None:
        return pod.__dict__["_lzraw"].get("spec") or {}
    sd = spec.__dict__
    for f in _LAZY_SPEC_FIELDS:
        if f in sd:
            return None
    return sd["_lzraw"]


def undecoded_meta(obj) -> Optional[dict]:
    """The raw metadata dict while ``obj.meta`` is undecoded — covers
    both the sectioned wrappers and the generic full-promotion wrappers
    (promotion/explicit writes land ``meta`` in the instance dict)."""
    d = getattr(obj, "__dict__", None)
    if not d:
        return None
    raw = d.get("_lzraw")
    if raw is None or "meta" in d or d.get("_lz_done"):
        return None
    return raw.get("metadata") or {}


def resource_version_of(obj) -> int:
    m = undecoded_meta(obj)
    if m is not None:
        return int(m.get("resourceVersion", 0))
    return getattr(obj.meta, "resource_version", 0)


def labels_ns_of(obj) -> tuple[dict, str]:
    """(labels, namespace) without building an ObjectMeta when possible —
    the HostBatchState ingest reader (O(cluster) on rebuild)."""
    m = undecoded_meta(obj)
    if m is not None:
        return (m.get("labels") or {}, m.get("namespace", "default"))
    meta = obj.meta
    if type(meta) is LazyObjectMeta and "labels" not in meta.__dict__:
        return (meta.raw.get("labels") or {}, meta.namespace)
    return (meta.labels, meta.namespace)


def pod_brief(pod) -> tuple[str, str, str]:
    """(node_name, scheduler_name, phase) at the cheapest depth available
    — the scheduler's informer handlers route EVERY pod event on exactly
    these three fields, and building a spec/status view per event was
    measurable at wave scale."""
    if type(pod) is LazyPod:
        d = pod.__dict__
        spec = d.get("spec")
        if spec is None:
            rs = d["_lzraw"].get("spec") or {}
            node_name = rs.get("nodeName", "")
            sched_name = rs.get("schedulerName", "default-scheduler")
        else:
            node_name = spec.node_name
            sched_name = spec.scheduler_name
        if "status" in d:
            phase = d["status"].phase
        else:
            phase = (d["_lzraw"].get("status") or {}).get("phase", api.PENDING)
        return node_name, sched_name, phase
    return pod.spec.node_name, pod.spec.scheduler_name, pod.status.phase


def raw_host_ports(spec: dict) -> list[tuple[str, int]]:
    out = []
    for c in spec.get("containers") or []:
        for p in c.get("ports") or []:
            hp = p.get("hostPort", 0)
            if hp > 0:
                out.append((p.get("protocol", "TCP"), hp))
    return out


def raw_has_affinity(spec: dict) -> bool:
    a = spec.get("affinity")
    return bool(a) and bool(
        a.get("podAffinityRequired") or a.get("podAffinityPreferred")
        or a.get("podAntiAffinityRequired") or a.get("podAntiAffinityPreferred"))


def raw_controller_ref(meta: dict) -> Optional[tuple[str, str]]:
    for ref in meta.get("ownerReferences") or []:
        if ref.get("controller"):
            return (ref.get("kind", ""), ref.get("uid", ""))
    return None
