"""Dynamic resource registration: the CRD analogue.

Capability of ``staging/src/k8s.io/apiextensions-apiserver`` (6.8k LoC):
a ``CustomResourceDefinition`` object names a new kind; once established,
that kind is a first-class citizen of the one type registry — typed
clients, informers, the wire apiserver's lazy resource lookup, kubectl's
registry-driven resource resolution, and the garbage collector's
registry-wide owner graph all pick it up with no further wiring (that is
the point of routing EVERYTHING through ``api.types.KINDS``).

Custom objects are schema-less wire dicts (the era's CRDs had no
validation schema either): ``DynamicObject`` keeps the raw dict and
exposes the standard ``meta`` / ``to_dict`` / ``from_dict`` surface every
framework component expects.

``CRDRegistrar`` is the controller loop (the apiextensions controller's
establish path): watch CRD objects, register/unregister kinds at
runtime."""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional

from .meta import ObjectMeta
from .types import (
    CLUSTER_SCOPED_KINDS,
    KIND_PLURALS,
    KINDS,
    register_cluster_scoped,
)


@dataclass
class DynamicObject:
    """A schema-less custom object: ObjectMeta + opaque payload."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    raw: dict = field(default_factory=dict)  # everything except kind/metadata

    KIND = "DynamicObject"  # overridden per registered class

    def to_dict(self) -> dict:
        d = copy.deepcopy(self.raw)
        d["kind"] = self.KIND
        d["metadata"] = self.meta.to_dict()
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "DynamicObject":
        raw = {k: copy.deepcopy(v) for k, v in d.items() if k not in ("kind", "metadata")}
        return cls(meta=ObjectMeta.from_dict(d.get("metadata") or {}), raw=raw)


def make_dynamic_kind(kind: str) -> type:
    """Mint a DynamicObject subclass whose KIND is ``kind``."""
    return type(kind, (DynamicObject,), {"KIND": kind})


@register_cluster_scoped
@dataclass
class CustomResourceDefinition:
    """The definition object (reference ``apiextensions/v1beta1.
    CustomResourceDefinition``): names.kind + names.plural + scope."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    kind_name: str = ""  # the custom kind, e.g. "Widget"
    plural: str = ""  # REST resource segment, e.g. "widgets"
    scope: str = "Namespaced"  # Namespaced | Cluster
    established: bool = False  # status: accepted + registered

    KIND = "CustomResourceDefinition"

    def __post_init__(self):
        self.meta.namespace = ""

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "names": {"kind": self.kind_name, "plural": self.plural},
                "scope": self.scope,
            },
            "status": {"established": self.established},
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CustomResourceDefinition":
        meta = ObjectMeta.from_dict(d.get("metadata") or {})
        meta.namespace = ""
        spec = d.get("spec") or {}
        names = spec.get("names") or {}
        return cls(
            meta=meta,
            kind_name=names.get("kind", ""),
            plural=names.get("plural", ""),
            scope=spec.get("scope", "Namespaced"),
            established=bool((d.get("status") or {}).get("established")),
        )


def register_custom_kind(crd: CustomResourceDefinition) -> Optional[type]:
    """Establish a CRD: add its kind to the live registry (idempotent).
    Returns the dynamic class, or None if the kind name collides with a
    built-in of a different shape."""
    if not crd.kind_name or not crd.plural:
        return None
    existing = KINDS.get(crd.kind_name)
    if existing is not None:
        return existing if issubclass(existing, DynamicObject) else None
    cls = make_dynamic_kind(crd.kind_name)
    KINDS[crd.kind_name] = cls
    KIND_PLURALS[crd.kind_name] = crd.plural
    if crd.scope == "Cluster":
        CLUSTER_SCOPED_KINDS.add(crd.kind_name)
    return cls


def unregister_custom_kind(kind_name: str) -> None:
    """CRD deleted: the kind disappears from the registry (custom objects
    themselves are cleaned up by the namespace/GC machinery as usual)."""
    cls = KINDS.get(kind_name)
    if cls is not None and issubclass(cls, DynamicObject):
        KINDS.pop(kind_name, None)
        KIND_PLURALS.pop(kind_name, None)
        CLUSTER_SCOPED_KINDS.discard(kind_name)
