"""Core API types: Pod, Node, Binding, Service, ReplicaSet, …

Capability equivalent of the reference's internal hub types
(``pkg/api/types.go``, 4,121 lines) at the depth the framework needs:
everything the scheduler's predicates/priorities read, plus what the
controllers and hollow kubelet reconcile.  Wire form is JSON-shaped dicts
(``to_dict``/``from_dict``), the store's serialization unit.

Deliberately *not* hub-and-spoke versioned: there is a single internal
schema with explicit ``from_dict`` tolerance for missing fields, which is the
versioning seam if wire versions are added later.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Optional

from .meta import ObjectMeta, OwnerReference
from .quantity import Quantity
from .selectors import LabelSelector, NodeSelector

# -- resource names (reference pkg/api/types.go ResourceName consts) --------
CPU = "cpu"
MEMORY = "memory"
PODS = "pods"
EPHEMERAL_STORAGE = "ephemeral-storage"
GPU = "nvidia.com/gpu"  # reference-era ResourceNvidiaGPU / accelerator

# Pod phases
PENDING = "Pending"
RUNNING = "Running"
SUCCEEDED = "Succeeded"
FAILED = "Failed"

# Taint effects (reference pkg/api/types.go TaintEffect)
NO_SCHEDULE = "NoSchedule"
PREFER_NO_SCHEDULE = "PreferNoSchedule"
NO_EXECUTE = "NoExecute"

# the era's node-failure taint keys (taint_controller.go); applied by the
# node lifecycle controller, tolerated by DefaultTolerationSeconds
TAINT_NODE_NOT_READY = "node.alpha.kubernetes.io/notReady"
TAINT_NODE_UNREACHABLE = "node.alpha.kubernetes.io/unreachable"

# stamped on ReplicaSets by the deployment controller; read by kubectl
# rollout history/undo (reference deployment/util annotation constants)
DEPLOYMENT_REVISION_ANNOTATION = "deployment.kubernetes.io/revision"

# Node condition types
NODE_READY = "Ready"
NODE_MEMORY_PRESSURE = "MemoryPressure"
NODE_DISK_PRESSURE = "DiskPressure"

# QoS classes (reference pkg/api/v1/helper/qos)
GUARANTEED = "Guaranteed"
BURSTABLE = "Burstable"
BEST_EFFORT = "BestEffort"

# Well-known label keys
HOSTNAME_LABEL = "kubernetes.io/hostname"
ZONE_LABEL = "failure-domain.beta.kubernetes.io/zone"
REGION_LABEL = "failure-domain.beta.kubernetes.io/region"

ResourceList = dict  # resource name -> Quantity


def _res_to_dict(r: dict[str, Quantity]) -> dict:
    return {k: str(v) for k, v in r.items()}


def _res_from_dict(d: Optional[dict]) -> dict[str, Quantity]:
    return {k: Quantity(v) for k, v in (d or {}).items()}


# ---------------------------------------------------------------------------
# Pod
# ---------------------------------------------------------------------------


@dataclass
class ContainerPort:
    container_port: int = 0
    host_port: int = 0
    protocol: str = "TCP"
    host_ip: str = ""

    def to_dict(self) -> dict:
        return {
            "containerPort": self.container_port,
            "hostPort": self.host_port,
            "protocol": self.protocol,
            "hostIP": self.host_ip,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ContainerPort":
        return cls(
            container_port=int(d.get("containerPort", 0)),
            host_port=int(d.get("hostPort", 0)),
            protocol=d.get("protocol", "TCP"),
            host_ip=d.get("hostIP", ""),
        )


@dataclass
class ResourceRequirements:
    requests: dict[str, Quantity] = field(default_factory=dict)
    limits: dict[str, Quantity] = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "requests": _res_to_dict(self.requests),
            "limits": _res_to_dict(self.limits),
        }

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "ResourceRequirements":
        d = d or {}
        return cls(
            requests=_res_from_dict(d.get("requests")),
            limits=_res_from_dict(d.get("limits")),
        )


@dataclass
class Probe:
    """Liveness/readiness probe (reference ``pkg/api/types.go`` Probe;
    executed by ``pkg/kubelet/prober``).  ``handler`` is "exec" | "http" |
    "tcp"; the fake runtime interprets it."""

    handler: str = "exec"
    initial_delay_seconds: int = 0
    period_seconds: int = 10
    failure_threshold: int = 3
    success_threshold: int = 1
    # exec handler's command (``ExecAction.Command``): when set and the
    # node runs real containers, the prober runs it via CRI ExecSync and
    # judges by exit code (``prober/prober.go:80 runProbe``)
    exec_command: list[str] = field(default_factory=list)
    # the reference's Probe.TimeoutSeconds (default 1): a hung probe
    # command is a FAILURE after this bound, never an unbounded wait
    timeout_seconds: int = 1

    def to_dict(self) -> dict:
        d = {
            "handler": self.handler,
            "initialDelaySeconds": self.initial_delay_seconds,
            "periodSeconds": self.period_seconds,
            "failureThreshold": self.failure_threshold,
            "successThreshold": self.success_threshold,
        }
        if self.exec_command:
            d["execCommand"] = list(self.exec_command)
        if self.timeout_seconds != 1:
            d["timeoutSeconds"] = self.timeout_seconds
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> Optional["Probe"]:
        if not d:
            return None
        return cls(
            handler=d.get("handler", "exec"),
            initial_delay_seconds=int(d.get("initialDelaySeconds", 0)),
            period_seconds=int(d.get("periodSeconds", 10)),
            failure_threshold=int(d.get("failureThreshold", 3)),
            success_threshold=int(d.get("successThreshold", 1)),
            exec_command=list(d.get("execCommand") or []),
            timeout_seconds=int(d.get("timeoutSeconds", 1)),
        )


@dataclass
class VolumeMount:
    """``VolumeMount``: where a pod volume appears in the container's
    rootfs (``pkg/api/types.go`` VolumeMount; materialized under the
    container's rootfs dir by the real-container runtime)."""

    name: str = ""
    mount_path: str = ""
    read_only: bool = False

    def to_dict(self) -> dict:
        return {"name": self.name, "mountPath": self.mount_path,
                "readOnly": self.read_only}

    @classmethod
    def from_dict(cls, d: dict) -> "VolumeMount":
        return cls(name=d.get("name", ""), mount_path=d.get("mountPath", ""),
                   read_only=bool(d.get("readOnly", False)))


@dataclass
class Container:
    name: str = ""
    image: str = ""
    resources: ResourceRequirements = field(default_factory=ResourceRequirements)
    ports: list[ContainerPort] = field(default_factory=list)
    liveness_probe: Optional[Probe] = None
    readiness_probe: Optional[Probe] = None
    env: dict[str, str] = field(default_factory=dict)  # injected by PodPreset
    image_pull_policy: str = ""  # "" | Always | IfNotPresent | Never
    privileged: bool = False  # securityContext.privileged essential
    run_as_user: Optional[int] = None  # securityContext.runAsUser (PSP ranges)
    # entrypoint (``Container.Command``/``Args`` collapsed): the real-
    # container runtime execs this; empty = the image's default (a pause
    # style sleep at this framework's depth)
    command: list[str] = field(default_factory=list)
    volume_mounts: list[VolumeMount] = field(default_factory=list)

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "image": self.image,
            "resources": self.resources.to_dict(),
            "ports": [p.to_dict() for p in self.ports],
        }
        if self.command:
            d["command"] = list(self.command)
        if self.volume_mounts:
            d["volumeMounts"] = [m.to_dict() for m in self.volume_mounts]
        if self.liveness_probe:
            d["livenessProbe"] = self.liveness_probe.to_dict()
        if self.readiness_probe:
            d["readinessProbe"] = self.readiness_probe.to_dict()
        if self.env:
            d["env"] = dict(self.env)
        if self.image_pull_policy:
            d["imagePullPolicy"] = self.image_pull_policy
        if self.privileged or self.run_as_user is not None:
            sc: dict = {}
            if self.privileged:
                sc["privileged"] = True
            if self.run_as_user is not None:
                sc["runAsUser"] = self.run_as_user
            d["securityContext"] = sc
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Container":
        return cls(
            name=d.get("name", ""),
            image=d.get("image", ""),
            resources=ResourceRequirements.from_dict(d.get("resources")),
            ports=[ContainerPort.from_dict(p) for p in d.get("ports") or []],
            liveness_probe=Probe.from_dict(d.get("livenessProbe")),
            readiness_probe=Probe.from_dict(d.get("readinessProbe")),
            env=dict(d.get("env") or {}),
            image_pull_policy=d.get("imagePullPolicy", ""),
            privileged=bool((d.get("securityContext") or {}).get("privileged")),
            run_as_user=(d.get("securityContext") or {}).get("runAsUser"),
            command=list(d.get("command") or []),
            volume_mounts=[VolumeMount.from_dict(m)
                           for m in d.get("volumeMounts") or []],
        )


@dataclass
class Volume:
    """Simplified volume: only what scheduling predicates consume.

    ``disk_id`` models the exclusive-attachment id behind NoDiskConflict /
    Max*VolumeCount (GCEPersistentDisk pdName, AWSElasticBlockStore volumeID,
    RBD image, ISCSI iqn — reference ``predicates.go:121-183``).
    ``pvc_name`` models persistentVolumeClaim references (zone conflict /
    volume-node predicates).
    """

    name: str = ""
    disk_id: str = ""
    disk_kind: str = ""  # "gce-pd" | "aws-ebs" | "azure-disk" | "rbd" | "iscsi" | ""
    read_only: bool = False
    pvc_name: str = ""
    secret_name: str = ""  # secret-backed volume (kubelet mounts, node authz)
    config_map_name: str = ""
    # local volume types the real-container kubelet materializes on disk
    # (reference ``pkg/volume/{empty_dir,host_path,downwardapi}``)
    empty_dir: bool = False
    host_path: str = ""
    # downwardAPI: file name -> fieldRef path ("metadata.name",
    # "metadata.namespace", "metadata.labels['k']", "metadata.annotations['k']")
    downward_api: dict[str, str] = field(default_factory=dict)

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "diskID": self.disk_id,
            "diskKind": self.disk_kind,
            "readOnly": self.read_only,
            "pvcName": self.pvc_name,
            "secretName": self.secret_name,
            "configMapName": self.config_map_name,
        }
        if self.empty_dir:
            d["emptyDir"] = True
        if self.host_path:
            d["hostPath"] = self.host_path
        if self.downward_api:
            d["downwardAPI"] = dict(self.downward_api)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Volume":
        return cls(
            name=d.get("name", ""),
            disk_id=d.get("diskID", ""),
            disk_kind=d.get("diskKind", ""),
            read_only=bool(d.get("readOnly", False)),
            pvc_name=d.get("pvcName", ""),
            secret_name=d.get("secretName", ""),
            config_map_name=d.get("configMapName", ""),
            empty_dir=bool(d.get("emptyDir", False)),
            host_path=d.get("hostPath", ""),
            downward_api=dict(d.get("downwardAPI") or {}),
        )


@dataclass
class Toleration:
    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" tolerates all effects
    toleration_seconds: Optional[int] = None

    def tolerates(self, taint: "Taint") -> bool:
        """Reference ``pkg/api/v1/helper.TolerationsTolerateTaint`` semantics."""
        if self.effect and self.effect != taint.effect:
            return False
        if self.key and self.key != taint.key:
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value

    def to_dict(self) -> dict:
        d = {
            "key": self.key,
            "operator": self.operator,
            "value": self.value,
            "effect": self.effect,
        }
        if self.toleration_seconds is not None:
            d["tolerationSeconds"] = self.toleration_seconds
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Toleration":
        return cls(
            key=d.get("key", ""),
            operator=d.get("operator", "Equal"),
            value=d.get("value", ""),
            effect=d.get("effect", ""),
            toleration_seconds=d.get("tolerationSeconds"),
        )


@dataclass
class Taint:
    key: str = ""
    value: str = ""
    effect: str = NO_SCHEDULE

    def to_dict(self) -> dict:
        return {"key": self.key, "value": self.value, "effect": self.effect}

    @classmethod
    def from_dict(cls, d: dict) -> "Taint":
        return cls(d.get("key", ""), d.get("value", ""), d.get("effect", NO_SCHEDULE))


@dataclass
class PodAffinityTerm:
    """One (anti)affinity term (``v1.PodAffinityTerm``): pods selected by
    ``selector`` in ``namespaces`` (empty → the term-owner pod's namespace),
    spread/packed over ``topology_key``."""

    selector: Optional[LabelSelector] = None
    topology_key: str = HOSTNAME_LABEL
    namespaces: list[str] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "labelSelector": self.selector.to_dict() if self.selector else None,
            "topologyKey": self.topology_key,
            "namespaces": list(self.namespaces),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "PodAffinityTerm":
        sel = d.get("labelSelector")
        return cls(
            selector=LabelSelector.from_dict(sel) if sel is not None else None,
            topology_key=d.get("topologyKey", HOSTNAME_LABEL),
            namespaces=list(d.get("namespaces") or []),
        )


@dataclass
class WeightedPodAffinityTerm:
    weight: int = 1
    term: PodAffinityTerm = field(default_factory=PodAffinityTerm)

    def to_dict(self) -> dict:
        return {"weight": self.weight, "podAffinityTerm": self.term.to_dict()}

    @classmethod
    def from_dict(cls, d: dict) -> "WeightedPodAffinityTerm":
        return cls(int(d.get("weight", 1)), PodAffinityTerm.from_dict(d.get("podAffinityTerm") or {}))


@dataclass
class PreferredSchedulingTerm:
    weight: int = 1
    preference: "NodeSelectorTermRef" = None  # NodeSelectorTerm

    def to_dict(self) -> dict:
        return {"weight": self.weight, "preference": self.preference.to_dict()}

    @classmethod
    def from_dict(cls, d: dict) -> "PreferredSchedulingTerm":
        from .selectors import NodeSelectorTerm

        return cls(int(d.get("weight", 1)), NodeSelectorTerm.from_dict(d.get("preference") or {}))


NodeSelectorTermRef = object  # forward-typing convenience


@dataclass
class Affinity:
    node_affinity_required: Optional[NodeSelector] = None
    node_affinity_preferred: list[PreferredSchedulingTerm] = field(default_factory=list)
    pod_affinity_required: list[PodAffinityTerm] = field(default_factory=list)
    pod_affinity_preferred: list[WeightedPodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity_required: list[PodAffinityTerm] = field(default_factory=list)
    pod_anti_affinity_preferred: list[WeightedPodAffinityTerm] = field(default_factory=list)

    def is_empty(self) -> bool:
        return not (
            self.node_affinity_required
            or self.node_affinity_preferred
            or self.pod_affinity_required
            or self.pod_affinity_preferred
            or self.pod_anti_affinity_required
            or self.pod_anti_affinity_preferred
        )

    def to_dict(self) -> dict:
        return {
            "nodeAffinityRequired": self.node_affinity_required.to_dict()
            if self.node_affinity_required
            else None,
            "nodeAffinityPreferred": [t.to_dict() for t in self.node_affinity_preferred],
            "podAffinityRequired": [t.to_dict() for t in self.pod_affinity_required],
            "podAffinityPreferred": [t.to_dict() for t in self.pod_affinity_preferred],
            "podAntiAffinityRequired": [t.to_dict() for t in self.pod_anti_affinity_required],
            "podAntiAffinityPreferred": [t.to_dict() for t in self.pod_anti_affinity_preferred],
        }

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "Optional[Affinity]":
        if not d:
            return None
        return cls(
            node_affinity_required=NodeSelector.from_dict(d.get("nodeAffinityRequired")),
            node_affinity_preferred=[
                PreferredSchedulingTerm.from_dict(t) for t in d.get("nodeAffinityPreferred") or []
            ],
            pod_affinity_required=[
                PodAffinityTerm.from_dict(t) for t in d.get("podAffinityRequired") or []
            ],
            pod_affinity_preferred=[
                WeightedPodAffinityTerm.from_dict(t) for t in d.get("podAffinityPreferred") or []
            ],
            pod_anti_affinity_required=[
                PodAffinityTerm.from_dict(t) for t in d.get("podAntiAffinityRequired") or []
            ],
            pod_anti_affinity_preferred=[
                WeightedPodAffinityTerm.from_dict(t) for t in d.get("podAntiAffinityPreferred") or []
            ],
        )


@dataclass
class PodSpec:
    containers: list[Container] = field(default_factory=list)
    node_name: str = ""
    node_selector: dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    tolerations: list[Toleration] = field(default_factory=list)
    volumes: list[Volume] = field(default_factory=list)
    priority: int = 0
    priority_class_name: str = ""
    scheduler_name: str = "default-scheduler"
    restart_policy: str = "Always"
    service_account_name: str = ""
    termination_grace_period_seconds: int = 30
    active_deadline_seconds: Optional[int] = None
    # host namespace sharing (PSP/DenyEscalatingExec gates read these)
    host_pid: bool = False
    host_ipc: bool = False
    host_network: bool = False

    def to_dict(self) -> dict:
        return {
            "containers": [c.to_dict() for c in self.containers],
            "nodeName": self.node_name,
            "nodeSelector": dict(self.node_selector),
            "affinity": self.affinity.to_dict() if self.affinity else None,
            "tolerations": [t.to_dict() for t in self.tolerations],
            "volumes": [v.to_dict() for v in self.volumes],
            "priority": self.priority,
            "priorityClassName": self.priority_class_name,
            "schedulerName": self.scheduler_name,
            "restartPolicy": self.restart_policy,
            "serviceAccountName": self.service_account_name,
            "terminationGracePeriodSeconds": self.termination_grace_period_seconds,
            "activeDeadlineSeconds": self.active_deadline_seconds,
            "hostPID": self.host_pid,
            "hostIPC": self.host_ipc,
            "hostNetwork": self.host_network,
        }

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "PodSpec":
        d = d or {}
        ads = d.get("activeDeadlineSeconds")
        return cls(
            containers=[Container.from_dict(c) for c in d.get("containers") or []],
            node_name=d.get("nodeName", ""),
            node_selector=dict(d.get("nodeSelector") or {}),
            affinity=Affinity.from_dict(d.get("affinity")),
            tolerations=[Toleration.from_dict(t) for t in d.get("tolerations") or []],
            volumes=[Volume.from_dict(v) for v in d.get("volumes") or []],
            priority=int(d.get("priority", 0)),
            priority_class_name=d.get("priorityClassName", ""),
            scheduler_name=d.get("schedulerName", "default-scheduler"),
            restart_policy=d.get("restartPolicy", "Always"),
            service_account_name=d.get("serviceAccountName", ""),
            termination_grace_period_seconds=int(d.get("terminationGracePeriodSeconds", 30)),
            active_deadline_seconds=None if ads is None else int(ads),
            host_pid=bool(d.get("hostPID", False)),
            host_ipc=bool(d.get("hostIPC", False)),
            host_network=bool(d.get("hostNetwork", False)),
        )


@dataclass
class ContainerStatus:
    """Per-container runtime state (reference ``pkg/api/types.go``
    ContainerStatus; written by the kubelet status manager)."""

    name: str = ""
    state: str = "waiting"  # waiting | running | terminated
    ready: bool = False
    restart_count: int = 0
    exit_code: int = 0
    reason: str = ""
    # runtime handle ("pid://<n>" under the real-container runtime) —
    # the reference's containerID ("docker://<hash>")
    container_id: str = ""

    def to_dict(self) -> dict:
        d = {
            "name": self.name,
            "state": self.state,
            "ready": self.ready,
            "restartCount": self.restart_count,
            "exitCode": self.exit_code,
            "reason": self.reason,
        }
        if self.container_id:
            d["containerID"] = self.container_id
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "ContainerStatus":
        return cls(
            name=d.get("name", ""),
            state=d.get("state", "waiting"),
            ready=bool(d.get("ready", False)),
            restart_count=int(d.get("restartCount", 0)),
            exit_code=int(d.get("exitCode", 0)),
            reason=d.get("reason", ""),
            container_id=d.get("containerID", ""),
        )


@dataclass
class PodStatus:
    phase: str = PENDING
    conditions: list[dict] = field(default_factory=list)
    host_ip: str = ""
    pod_ip: str = ""
    start_revision: int = 0
    container_statuses: list[ContainerStatus] = field(default_factory=list)
    reason: str = ""

    def to_dict(self) -> dict:
        d = {
            "phase": self.phase,
            "conditions": copy.deepcopy(self.conditions),
            "hostIP": self.host_ip,
            "podIP": self.pod_ip,
            "startRevision": self.start_revision,
        }
        if self.container_statuses:
            d["containerStatuses"] = [c.to_dict() for c in self.container_statuses]
        if self.reason:
            d["reason"] = self.reason
        return d

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "PodStatus":
        d = d or {}
        return cls(
            phase=d.get("phase", PENDING),
            conditions=copy.deepcopy(d.get("conditions") or []),
            host_ip=d.get("hostIP", ""),
            pod_ip=d.get("podIP", ""),
            start_revision=int(d.get("startRevision", 0)),
            container_statuses=[
                ContainerStatus.from_dict(c) for c in d.get("containerStatuses") or []
            ],
            reason=d.get("reason", ""),
        )


@dataclass
class Pod:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: PodSpec = field(default_factory=PodSpec)
    status: PodStatus = field(default_factory=PodStatus)

    KIND = "Pod"

    # -- scheduling helpers ------------------------------------------------
    def resource_requests(self) -> dict[str, Quantity]:
        """Summed container requests (reference ``predicates.GetResourceRequest``)."""
        total: dict[str, Quantity] = {}
        for c in self.spec.containers:
            for name, q in c.resources.requests.items():
                total[name] = total.get(name, Quantity(0)) + q
        return total

    def qos_class(self) -> str:
        """Reference ``pkg/api/v1/helper/qos.GetPodQOS`` semantics (cpu+mem)."""
        requests: dict[str, Quantity] = {}
        limits: dict[str, Quantity] = {}
        guaranteed = True
        for c in self.spec.containers:
            for name in (CPU, MEMORY):
                q = c.resources.requests.get(name)
                if q is not None and not q.is_zero():
                    requests[name] = requests.get(name, Quantity(0)) + q
                lim = c.resources.limits.get(name)
                if lim is not None and not lim.is_zero():
                    limits[name] = limits.get(name, Quantity(0)) + lim
                else:
                    guaranteed = False
        if not requests and not limits:
            return BEST_EFFORT
        if guaranteed and all(requests.get(n) == limits.get(n) for n in (CPU, MEMORY)):
            return GUARANTEED
        return BURSTABLE

    def host_ports(self) -> list[tuple[str, int]]:
        out = []
        for c in self.spec.containers:
            for p in c.ports:
                if p.host_port > 0:
                    out.append((p.protocol, p.host_port))
        return out

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Pod":
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=PodSpec.from_dict(d.get("spec")),
            status=PodStatus.from_dict(d.get("status")),
        )


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------


@dataclass
class NodeCondition:
    type: str = ""
    status: str = "False"  # "True" | "False" | "Unknown"
    heartbeat_revision: int = 0
    heartbeat_time: float = 0.0  # injected-clock seconds (kubelet heartbeat)

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "status": self.status,
            "heartbeatRevision": self.heartbeat_revision,
            "heartbeatTime": self.heartbeat_time,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "NodeCondition":
        return cls(
            type=d.get("type", ""),
            status=d.get("status", "False"),
            heartbeat_revision=int(d.get("heartbeatRevision", 0)),
            heartbeat_time=float(d.get("heartbeatTime", 0.0)),
        )


@dataclass
class NodeSpec:
    taints: list[Taint] = field(default_factory=list)
    unschedulable: bool = False
    provider_id: str = ""
    pod_cidr: str = ""  # allocated by the node IPAM controller

    def to_dict(self) -> dict:
        return {
            "taints": [t.to_dict() for t in self.taints],
            "unschedulable": self.unschedulable,
            "providerID": self.provider_id,
            "podCIDR": self.pod_cidr,
        }

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "NodeSpec":
        d = d or {}
        return cls(
            taints=[Taint.from_dict(t) for t in d.get("taints") or []],
            unschedulable=bool(d.get("unschedulable", False)),
            provider_id=d.get("providerID", ""),
            pod_cidr=d.get("podCIDR", ""),
        )


@dataclass
class NodeStatus:
    capacity: dict[str, Quantity] = field(default_factory=dict)
    allocatable: dict[str, Quantity] = field(default_factory=dict)
    conditions: list[NodeCondition] = field(default_factory=list)
    images: list[dict] = field(default_factory=list)  # {"names": [...], "sizeBytes": N}
    # PV names attached to this node, written by the attach/detach
    # controller (reference ``node.status.volumesAttached``)
    volumes_attached: list[str] = field(default_factory=list)
    # the node's read-API endpoint (reference daemonEndpoints.kubeletEndpoint
    # + addresses, collapsed to one URL) — the apiserver proxies pod
    # subresources (logs) here
    kubelet_url: str = ""
    # PVs the kubelet currently has MOUNTED into pods (reference
    # ``node.status.volumesInUse``): the attach/detach controller must not
    # detach these until the kubelet unmounts
    volumes_in_use: list[str] = field(default_factory=list)
    # [{"type": "InternalIP"|"ExternalIP"|"Hostname", "address": ...}] —
    # written by the cloud node controller (reference node.status.addresses)
    addresses: list[dict] = field(default_factory=list)

    def condition(self, ctype: str) -> Optional[NodeCondition]:
        for c in self.conditions:
            if c.type == ctype:
                return c
        return None

    def to_dict(self) -> dict:
        return {
            "capacity": _res_to_dict(self.capacity),
            "allocatable": _res_to_dict(self.allocatable),
            "conditions": [c.to_dict() for c in self.conditions],
            "images": copy.deepcopy(self.images),
            "volumesAttached": list(self.volumes_attached),
            "kubeletURL": self.kubelet_url,
            "volumesInUse": list(self.volumes_in_use),
            "addresses": copy.deepcopy(self.addresses),
        }

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "NodeStatus":
        d = d or {}
        return cls(
            capacity=_res_from_dict(d.get("capacity")),
            allocatable=_res_from_dict(d.get("allocatable")),
            conditions=[NodeCondition.from_dict(c) for c in d.get("conditions") or []],
            images=copy.deepcopy(d.get("images") or []),
            volumes_attached=list(d.get("volumesAttached") or []),
            kubelet_url=d.get("kubeletURL", ""),
            volumes_in_use=list(d.get("volumesInUse") or []),
            addresses=copy.deepcopy(d.get("addresses") or []),
        )


@dataclass
class Node:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    spec: NodeSpec = field(default_factory=NodeSpec)
    status: NodeStatus = field(default_factory=NodeStatus)

    KIND = "Node"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": self.spec.to_dict(),
            "status": self.status.to_dict(),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            spec=NodeSpec.from_dict(d.get("spec")),
            status=NodeStatus.from_dict(d.get("status")),
        )


# ---------------------------------------------------------------------------
# Binding — the scheduler's commit object
# (reference pkg/registry/core/pod/storage/storage.go:128 BindingREST)
# ---------------------------------------------------------------------------


@dataclass
class Binding:
    pod_namespace: str = "default"
    pod_name: str = ""
    node_name: str = ""

    KIND = "Binding"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "podNamespace": self.pod_namespace,
            "podName": self.pod_name,
            "nodeName": self.node_name,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Binding":
        return cls(
            pod_namespace=d.get("podNamespace", "default"),
            pod_name=d.get("podName", ""),
            node_name=d.get("nodeName", ""),
        )


# ---------------------------------------------------------------------------
# Workload / grouping objects (controllers + SelectorSpreadPriority)
# ---------------------------------------------------------------------------


@dataclass
class ServicePort:
    """Service port mapping (reference ``pkg/api/types.go`` ServicePort;
    consumed by the proxy's NAT rule synthesis and the endpoint controller)."""

    name: str = ""
    protocol: str = "TCP"
    port: int = 0
    target_port: int = 0
    node_port: int = 0

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "protocol": self.protocol,
            "port": self.port,
            "targetPort": self.target_port,
            "nodePort": self.node_port,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ServicePort":
        return cls(
            name=d.get("name", ""),
            protocol=d.get("protocol", "TCP"),
            port=int(d.get("port", 0)),
            target_port=int(d.get("targetPort", 0)),
            node_port=int(d.get("nodePort", 0)),
        )


@dataclass
class Service:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    selector: dict[str, str] = field(default_factory=dict)
    ports: list[ServicePort] = field(default_factory=list)
    cluster_ip: str = ""  # "" = allocate; "None" = headless
    type: str = "ClusterIP"  # ClusterIP | NodePort | LoadBalancer
    session_affinity: str = "None"  # None | ClientIP
    # ingress IPs written by the cloud service controller for
    # type=LoadBalancer (reference ``status.loadBalancer.ingress``)
    status_load_balancer: list[str] = field(default_factory=list)

    KIND = "Service"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "selector": dict(self.selector),
                "ports": [p.to_dict() for p in self.ports],
                "clusterIP": self.cluster_ip,
                "type": self.type,
                "sessionAffinity": self.session_affinity,
            },
            "status": {
                "loadBalancer": {
                    "ingress": [{"ip": ip} for ip in self.status_load_balancer]
                }
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Service":
        spec = d.get("spec") or {}
        lb = ((d.get("status") or {}).get("loadBalancer") or {})
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            selector=dict(spec.get("selector") or {}),
            ports=[ServicePort.from_dict(p) for p in spec.get("ports") or []],
            cluster_ip=spec.get("clusterIP", ""),
            type=spec.get("type", "ClusterIP"),
            session_affinity=spec.get("sessionAffinity", "None"),
            status_load_balancer=[
                i.get("ip", "") for i in lb.get("ingress") or [] if i.get("ip")
            ],
        )


@dataclass
class PodTemplateSpec:
    labels: dict[str, str] = field(default_factory=dict)
    spec: PodSpec = field(default_factory=PodSpec)

    def to_dict(self) -> dict:
        return {"metadata": {"labels": dict(self.labels)}, "spec": self.spec.to_dict()}

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "PodTemplateSpec":
        d = d or {}
        return cls(
            labels=dict((d.get("metadata") or {}).get("labels") or {}),
            spec=PodSpec.from_dict(d.get("spec")),
        )


@dataclass
class ReplicaSet:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    replicas: int = 1
    selector: LabelSelector = field(default_factory=LabelSelector)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    status_replicas: int = 0
    status_ready_replicas: int = 0
    status_observed_generation: int = 0

    KIND = "ReplicaSet"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "replicas": self.replicas,
                "selector": self.selector.to_dict(),
                "template": self.template.to_dict(),
            },
            "status": {
                "replicas": self.status_replicas,
                "readyReplicas": self.status_ready_replicas,
                "observedGeneration": self.status_observed_generation,
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ReplicaSet":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            replicas=int(spec.get("replicas", 1)),
            selector=LabelSelector.from_dict(spec.get("selector")),
            template=PodTemplateSpec.from_dict(spec.get("template")),
            status_replicas=int(status.get("replicas", 0)),
            status_ready_replicas=int(status.get("readyReplicas", 0)),
            status_observed_generation=int(status.get("observedGeneration", 0)),
        )


@dataclass
class ReplicationController:
    """The original replica-keeper (reference ``pkg/api/types.go:2533``).
    Semantically ReplicaSet with a plain map selector (no set-based
    expressions); era tooling (``kubectl rolling-update``) was RC-based.
    Defaulting mirrors v1: an empty selector falls back to the template
    labels."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    replicas: int = 1
    selector_labels: dict = field(default_factory=dict)  # spec.selector map
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    status_replicas: int = 0
    status_ready_replicas: int = 0
    status_observed_generation: int = 0

    KIND = "ReplicationController"

    @property
    def selector(self) -> LabelSelector:
        """Map selector as a LabelSelector, with the v1 default-to-
        template-labels rule — lets RC share the ReplicaSet controller
        and kubectl machinery."""
        return LabelSelector.from_match_labels(
            self.selector_labels or self.template.labels)

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "replicas": self.replicas,
                "selector": dict(self.selector_labels),
                "template": self.template.to_dict(),
            },
            "status": {
                "replicas": self.status_replicas,
                "readyReplicas": self.status_ready_replicas,
                "observedGeneration": self.status_observed_generation,
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "ReplicationController":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            replicas=int(spec.get("replicas", 1)),
            selector_labels=dict(spec.get("selector") or {}),
            template=PodTemplateSpec.from_dict(spec.get("template")),
            status_replicas=int(status.get("replicas", 0)),
            status_ready_replicas=int(status.get("readyReplicas", 0)),
            status_observed_generation=int(status.get("observedGeneration", 0)),
        )


@dataclass
class Deployment:
    meta: ObjectMeta = field(default_factory=ObjectMeta)
    replicas: int = 1
    selector: LabelSelector = field(default_factory=LabelSelector)
    template: PodTemplateSpec = field(default_factory=PodTemplateSpec)
    strategy: str = "RollingUpdate"  # or "Recreate"
    max_surge: int = 1
    max_unavailable: int = 0
    # kubectl rollout pause/resume (deployment/sync.go: a paused
    # deployment reconciles SCALE but never progresses the rollout)
    paused: bool = False
    status_replicas: int = 0
    status_updated_replicas: int = 0
    status_ready_replicas: int = 0
    status_observed_generation: int = 0

    KIND = "Deployment"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "spec": {
                "replicas": self.replicas,
                "selector": self.selector.to_dict(),
                "template": self.template.to_dict(),
                "strategy": self.strategy,
                "maxSurge": self.max_surge,
                "maxUnavailable": self.max_unavailable,
                "paused": self.paused,
            },
            "status": {
                "replicas": self.status_replicas,
                "updatedReplicas": self.status_updated_replicas,
                "readyReplicas": self.status_ready_replicas,
                "observedGeneration": self.status_observed_generation,
            },
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Deployment":
        spec = d.get("spec") or {}
        status = d.get("status") or {}
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            replicas=int(spec.get("replicas", 1)),
            selector=LabelSelector.from_dict(spec.get("selector")),
            template=PodTemplateSpec.from_dict(spec.get("template")),
            strategy=spec.get("strategy", "RollingUpdate"),
            max_surge=int(spec.get("maxSurge", 1)),
            max_unavailable=int(spec.get("maxUnavailable", 0)),
            paused=bool(spec.get("paused", False)),
            status_replicas=int(status.get("replicas", 0)),
            status_updated_replicas=int(status.get("updatedReplicas", 0)),
            status_ready_replicas=int(status.get("readyReplicas", 0)),
            status_observed_generation=int(status.get("observedGeneration", 0)),
        )


@dataclass
class Event:
    """Cluster events (reference ``client-go/tools/record``): scheduler emits
    Scheduled / FailedScheduling (``scheduler.go:174,248``)."""

    meta: ObjectMeta = field(default_factory=ObjectMeta)
    involved_kind: str = ""
    involved_key: str = ""
    reason: str = ""
    message: str = ""
    type: str = "Normal"  # Normal | Warning
    count: int = 1

    KIND = "Event"

    def to_dict(self) -> dict:
        return {
            "kind": self.KIND,
            "metadata": self.meta.to_dict(),
            "involvedKind": self.involved_kind,
            "involvedKey": self.involved_key,
            "reason": self.reason,
            "message": self.message,
            "type": self.type,
            "count": self.count,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Event":
        return cls(
            meta=ObjectMeta.from_dict(d.get("metadata") or {}),
            involved_kind=d.get("involvedKind", ""),
            involved_key=d.get("involvedKey", ""),
            reason=d.get("reason", ""),
            message=d.get("message", ""),
            type=d.get("type", "Normal"),
            count=int(d.get("count", 1)),
        )


# Registry of kinds for the store / clients.  Sibling modules (apps,
# cluster, rbac) register their kinds at import — the runtime.Scheme
# analogue (reference apimachinery/pkg/runtime/scheme.go:569).  The
# clientset, kubectl, and the wire apiserver all derive their kind→resource
# tables from this one registry.
KINDS: dict[str, type] = {}

# Kinds whose objects live outside any namespace (store key = bare name).
CLUSTER_SCOPED_KINDS: set[str] = set()

# kind -> lowercase plural resource name (the REST path segment / kubectl
# resource argument, reference RESTMapper semantics).
KIND_PLURALS: dict[str, str] = {}


def _pluralize(kind: str) -> str:
    low = kind.lower()
    if low.endswith("ss"):  # PriorityClass -> priorityclasses
        return low + "es"
    if low.endswith("s"):  # Endpoints -> endpoints
        return low
    return low + "s"


def register_kind(cls, cluster_scoped: bool = False, plural: Optional[str] = None):
    KINDS[cls.KIND] = cls
    KIND_PLURALS[cls.KIND] = plural or _pluralize(cls.KIND)
    if cluster_scoped:
        CLUSTER_SCOPED_KINDS.add(cls.KIND)
    return cls


def kind_for_plural(plural: str) -> Optional[str]:
    """Resource segment -> kind, read from the live registry per call so
    late-registered (CRD-style) kinds resolve immediately.  Snapshots the
    registry so a concurrent register_kind can't break iteration."""
    for kind, p in list(KIND_PLURALS.items()):
        if p == plural:
            return kind
    return None


def register_cluster_scoped(cls):
    return register_kind(cls, cluster_scoped=True)


for _cls in (Pod, Service, ReplicaSet, ReplicationController, Deployment,
             Event):
    register_kind(_cls)
register_kind(Node, cluster_scoped=True)


def from_dict(d: dict):
    kind = d.get("kind", "")
    cls = KINDS.get(kind)
    if cls is None:
        raise ValueError(f"unknown kind {kind!r}")
    return cls.from_dict(d)
