"""cloud-controller-manager daemon (reference
``cmd/cloud-controller-manager/controller-manager.go``).

    python -m kubernetes_tpu.cloud --apiserver http://host:6443 \
        [--cloud-provider fake] [--leader-elect] [--controllers ...]
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
import threading

from ..daemon import install_signal_stop, remote_clientset, run_with_leader_election
from .manager import CLOUD_CONTROLLERS, CloudControllerManager
from .provider import FakeCloud


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubernetes_tpu.cloud")
    ap.add_argument("--apiserver", required=True)
    ap.add_argument("--token", default=None)
    ap.add_argument("--leader-elect", action="store_true")
    ap.add_argument("--cloud-provider", default="fake", choices=["fake"])
    ap.add_argument("--controllers", default="*",
                    help="comma list or * (default: %s)" % ",".join(CLOUD_CONTROLLERS))
    ap.add_argument("--workers", type=int, default=1)
    ap.add_argument("--monitor-period", type=float, default=5.0)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    cs = remote_clientset(args.apiserver, args.token)
    cloud = FakeCloud()
    names = None if args.controllers == "*" else args.controllers.split(",")

    def run(payload_stop: threading.Event) -> None:
        mgr = CloudControllerManager(cs, cloud, enabled=names)
        mgr.start(manual=False, workers_per_controller=args.workers)
        logging.info("cloud controller manager running: %s", ", ".join(mgr.controllers))
        while not payload_stop.is_set():
            mgr.tick()
            payload_stop.wait(args.monitor_period)
        mgr.stop()

    stop = install_signal_stop()
    run_with_leader_election(
        cs, "cloud-controller-manager", f"ccm-{os.getpid()}", run, stop,
        leader_elect=args.leader_elect,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
