"""Cloud provider layer (SURVEY.md §1-L6 cloud split:
``pkg/cloudprovider`` + ``cmd/cloud-controller-manager``)."""

from .controllers import CloudNodeController, RouteController, ServiceLBController
from .manager import CLOUD_CONTROLLERS, CloudControllerManager
from .provider import (
    CloudProvider,
    FakeCloud,
    Instance,
    LoadBalancer,
    Route,
)
