"""Cloud controllers: the loops the reference splits into
cloud-controller-manager (``cmd/cloud-controller-manager``,
``pkg/controller/cloud``, ``pkg/controller/service``,
``pkg/controller/route``).

All three coordinate purely through watched API objects and program the
IaaS through the :class:`~kubernetes_tpu.cloud.provider.CloudProvider`
surface — same level-triggered shape as every other controller here.
"""

from __future__ import annotations

from ..api import types as api
from ..controllers.base import Controller
from ..store.store import NotFoundError
from .provider import CloudProvider, Route

ZONE_LABEL = "failure-domain.beta.kubernetes.io/zone"
REGION_LABEL = "failure-domain.beta.kubernetes.io/region"


def _node_ready(node: api.Node) -> bool:
    cond = node.status.condition(api.NODE_READY)
    return cond is not None and cond.status == "True"


def _lb_name(namespace: str, name: str) -> str:
    """Cloud-unique LB name (reference ``GetLoadBalancerName`` uses
    "a"+UID).  Hash the key instead of joining with "-": namespaces and
    names may themselves contain hyphens, so a join would be ambiguous
    (team-a/web vs team/a-web) — and the hash stays derivable from the
    queue key alone after the Service object is gone."""
    import hashlib

    return "a" + hashlib.sha1(f"{namespace}/{name}".encode()).hexdigest()[:16]


class ServiceLBController(Controller):
    """``pkg/controller/service/servicecontroller.go``: for every Service
    of type=LoadBalancer, ensure a cloud LB pointing at the ready nodes
    and publish its ingress IP to service status; tear the LB down when
    the service is deleted or its type changes."""

    name = "service-lb"

    def __init__(self, clientset, informers=None, cloud: CloudProvider = None, **kw):
        super().__init__(clientset, informers, **kw)
        if cloud is None or cloud.load_balancer() is None:
            raise ValueError("ServiceLBController requires a cloud with LB support")
        self.lb = cloud.load_balancer()
        self.watch("Service")
        from ..client.informer import Handler

        # node churn re-targets every LB (reference nodeSyncLoop)
        self.informers.informer("Node").add_handler(Handler(
            on_add=lambda n: self._all_lb_services(),
            on_update=lambda old, new: (
                self._all_lb_services()
                if _node_ready(old) != _node_ready(new)
                or old.spec.unschedulable != new.spec.unschedulable
                else None
            ),
            on_delete=lambda n: self._all_lb_services(),
        ))

    def _all_lb_services(self) -> None:
        for svc in self.informer("Service").list():
            if svc.type == "LoadBalancer":
                self.queue.add(svc.meta.key)

    def _ready_nodes(self) -> list[str]:
        return sorted(
            n.meta.name for n in self.informer("Node").list()
            if _node_ready(n) and not n.spec.unschedulable
        )

    def sync(self, key: str) -> None:
        namespace, name = key.split("/", 1)
        lb_name = _lb_name(namespace, name)
        try:
            svc = self.clientset.services.get(name, namespace)
        except NotFoundError:
            self.lb.ensure_load_balancer_deleted(lb_name)
            return
        if svc.type != "LoadBalancer":
            # type changed away: release the cloud resource and any
            # previously published ingress
            self.lb.ensure_load_balancer_deleted(lb_name)
            if svc.status_load_balancer:
                def _clear(cur):
                    cur.status_load_balancer = []
                    return cur

                self.clientset.services.guaranteed_update(name, _clear, namespace)
            return
        ports = [p.port for p in svc.ports] or [80]
        lb = self.lb.ensure_load_balancer(lb_name, ports, self._ready_nodes())
        if svc.status_load_balancer != [lb.ingress_ip]:
            def _publish(cur):
                cur.status_load_balancer = [lb.ingress_ip]
                return cur

            self.clientset.services.guaranteed_update(name, _publish, namespace)


class RouteController(Controller):
    """``pkg/controller/route/routecontroller.go``: full-state reconcile of
    the cloud route table against node podCIDRs — one route per node with
    an allocated CIDR, stale routes removed."""

    name = "route"
    SYNC_KEY = "routes/all"

    def __init__(self, clientset, informers=None, cloud: CloudProvider = None,
                 cluster_name: str = "kubernetes", **kw):
        super().__init__(clientset, informers, **kw)
        if cloud is None or cloud.routes() is None:
            raise ValueError("RouteController requires a cloud with route support")
        self.routes = cloud.routes()
        self.cluster_name = cluster_name
        self.watch("Node", key_fn=lambda obj: self.SYNC_KEY)

    def sync(self, key: str) -> None:
        want: dict[str, str] = {
            n.meta.name: n.spec.pod_cidr
            for n in self.informer("Node").list() if n.spec.pod_cidr
        }
        have = {r.target_node: r for r in self.routes.list_routes()}
        for node, cidr in want.items():
            existing = have.get(node)
            if existing is None or existing.dest_cidr != cidr:
                if existing is not None:
                    self.routes.delete_route(existing)
                self.routes.create_route(Route(
                    name=f"{self.cluster_name}-{node}",
                    target_node=node, dest_cidr=cidr))
        for node, route in have.items():
            if node not in want:
                self.routes.delete_route(route)


class CloudNodeController(Controller):
    """``pkg/controller/cloud/nodecontroller.go``: stamp freshly registered
    nodes with their cloud addresses, zone/region labels and providerID;
    the periodic monitor deletes Node objects whose backing instance is
    gone from the cloud (the cloud half of node lifecycle)."""

    name = "cloud-node"

    def __init__(self, clientset, informers=None, cloud: CloudProvider = None, **kw):
        super().__init__(clientset, informers, **kw)
        if cloud is None or cloud.instances() is None:
            raise ValueError("CloudNodeController requires a cloud with instances")
        self.instances = cloud.instances()
        self.zones = cloud.zones()
        self.watch("Node")

    def sync(self, key: str) -> None:
        name = key.split("/", 1)[-1]
        try:
            node = self.clientset.nodes.get(name)
        except NotFoundError:
            return
        try:
            addresses = self.instances.node_addresses(name)
        except KeyError:
            return  # unknown to the cloud: the monitor decides its fate
        zone = region = ""
        if self.zones is not None:
            try:
                zone, region = self.zones.get_zone(name)
            except KeyError:
                pass
        needs_labels = (
            (zone and node.meta.labels.get(ZONE_LABEL) != zone)
            or (region and node.meta.labels.get(REGION_LABEL) != region)
        )
        if node.status.addresses == addresses and not needs_labels and node.spec.provider_id:
            return

        def _stamp(cur):
            cur.status.addresses = addresses
            if zone:
                cur.meta.labels[ZONE_LABEL] = zone
            if region:
                cur.meta.labels[REGION_LABEL] = region
            if not cur.spec.provider_id:
                cur.spec.provider_id = f"fake://{name}"
            return cur

        self.clientset.nodes.guaranteed_update(name, _stamp, "")

    def monitor(self) -> int:
        """Delete nodes whose cloud instance no longer exists (reference
        ``cloud/nodecontroller.go MonitorNode``)."""
        deleted = 0
        for node in list(self.informer("Node").list()):
            # only cloud-managed nodes (stamped with a providerID) are
            # subject to instance-existence deletion
            if not node.spec.provider_id:
                continue
            if not self.instances.instance_exists(node.meta.name):
                try:
                    self.clientset.nodes.delete(node.meta.name)
                    deleted += 1
                except NotFoundError:
                    pass
        return deleted
