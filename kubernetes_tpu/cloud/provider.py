"""Cloud provider interface + fake implementation.

Capability of the reference's ``pkg/cloudprovider`` (``cloud.go``
Interface with LoadBalancer()/Instances()/Zones()/Routes() accessors, ~10
provider adapters under ``providers/``) at the depth this control plane
consumes it: the cloud controllers (service LB, routes, node addresses,
instance-existence) program infrastructure through exactly this surface.

The only in-tree implementation is :class:`FakeCloud`, mirroring
``pkg/cloudprovider/providers/fake/fake.go`` — the reference's own test
double IS its contract for what a provider must do, and on this
TPU-resident control plane there is no real IaaS to call.  The call log
(``calls``) lets tests assert the controller→provider protocol exactly
the way the reference's service/route controller tests do.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class Instance:
    """One cloud VM (reference ``Instances.NodeAddresses`` /
    ``ExternalID``)."""

    name: str
    internal_ip: str = ""
    external_ip: str = ""
    zone: str = ""
    region: str = ""
    exists: bool = True


@dataclass
class LoadBalancer:
    """Provisioned LB state (reference ``LoadBalancerStatus``)."""

    name: str
    ingress_ip: str = ""
    ports: list[int] = field(default_factory=list)
    nodes: list[str] = field(default_factory=list)


@dataclass
class Route:
    """One inter-node route (reference ``routes.Route``)."""

    name: str
    target_node: str = ""
    dest_cidr: str = ""


class CloudProvider:
    """Reference ``cloudprovider.Interface``.  Accessors return None when
    the provider doesn't support that service (controllers skip work)."""

    def load_balancer(self) -> Optional["LoadBalancerService"]:
        return None

    def instances(self) -> Optional["InstancesService"]:
        return None

    def zones(self) -> Optional["ZonesService"]:
        return None

    def routes(self) -> Optional["RoutesService"]:
        return None


class LoadBalancerService:
    def get_load_balancer(self, name: str) -> Optional[LoadBalancer]:
        raise NotImplementedError

    def ensure_load_balancer(self, name: str, ports: list[int],
                             nodes: list[str]) -> LoadBalancer:
        raise NotImplementedError

    def update_load_balancer(self, name: str, nodes: list[str]) -> None:
        raise NotImplementedError

    def ensure_load_balancer_deleted(self, name: str) -> None:
        raise NotImplementedError


class InstancesService:
    def node_addresses(self, name: str) -> list[dict]:
        raise NotImplementedError

    def instance_exists(self, name: str) -> bool:
        raise NotImplementedError


class ZonesService:
    def get_zone(self, name: str) -> tuple[str, str]:
        """(zone, region) for an instance."""
        raise NotImplementedError


class RoutesService:
    def list_routes(self) -> list[Route]:
        raise NotImplementedError

    def create_route(self, route: Route) -> None:
        raise NotImplementedError

    def delete_route(self, route: Route) -> None:
        raise NotImplementedError


class FakeCloud(CloudProvider, LoadBalancerService, InstancesService,
                ZonesService, RoutesService):
    """In-memory provider (reference ``providers/fake``): deterministic LB
    IP allocation, instance registry, route table, and a call log for
    protocol assertions."""

    def __init__(self, ip_base: str = "203.0.113"):
        self._lock = threading.Lock()
        self.instances_by_name: dict[str, Instance] = {}
        self.balancers: dict[str, LoadBalancer] = {}
        self.route_table: dict[str, Route] = {}
        self.calls: list[tuple] = []
        self._ip_base = ip_base
        self._next_ip = 1

    # -- accessors (all services supported) --------------------------------
    def load_balancer(self):
        return self

    def instances(self):
        return self

    def zones(self):
        return self

    def routes(self):
        return self

    # -- instance admin (test setup) ---------------------------------------
    def add_instance(self, inst: Instance) -> None:
        with self._lock:
            self.instances_by_name[inst.name] = inst

    def remove_instance(self, name: str) -> None:
        with self._lock:
            if name in self.instances_by_name:
                self.instances_by_name[name].exists = False

    # -- LoadBalancerService ------------------------------------------------
    def get_load_balancer(self, name: str) -> Optional[LoadBalancer]:
        with self._lock:
            self.calls.append(("get", name))
            return self.balancers.get(name)

    def ensure_load_balancer(self, name, ports, nodes) -> LoadBalancer:
        with self._lock:
            self.calls.append(("ensure", name, tuple(ports), tuple(sorted(nodes))))
            lb = self.balancers.get(name)
            if lb is None:
                lb = LoadBalancer(name=name,
                                  ingress_ip=f"{self._ip_base}.{self._next_ip}")
                self._next_ip += 1
                self.balancers[name] = lb
            lb.ports = list(ports)
            lb.nodes = sorted(nodes)
            return lb

    def update_load_balancer(self, name, nodes) -> None:
        with self._lock:
            self.calls.append(("update", name, tuple(sorted(nodes))))
            if name in self.balancers:
                self.balancers[name].nodes = sorted(nodes)

    def ensure_load_balancer_deleted(self, name) -> None:
        with self._lock:
            self.calls.append(("delete", name))
            self.balancers.pop(name, None)

    # -- InstancesService ----------------------------------------------------
    def node_addresses(self, name: str) -> list[dict]:
        with self._lock:
            inst = self.instances_by_name.get(name)
            if inst is None or not inst.exists:
                raise KeyError(name)
            out = []
            if inst.internal_ip:
                out.append({"type": "InternalIP", "address": inst.internal_ip})
            if inst.external_ip:
                out.append({"type": "ExternalIP", "address": inst.external_ip})
            out.append({"type": "Hostname", "address": inst.name})
            return out

    def instance_exists(self, name: str) -> bool:
        with self._lock:
            inst = self.instances_by_name.get(name)
            return inst is not None and inst.exists

    # -- ZonesService --------------------------------------------------------
    def get_zone(self, name: str) -> tuple[str, str]:
        with self._lock:
            inst = self.instances_by_name.get(name)
            if inst is None:
                raise KeyError(name)
            return inst.zone, inst.region

    # -- RoutesService -------------------------------------------------------
    def list_routes(self) -> list[Route]:
        with self._lock:
            return list(self.route_table.values())

    def create_route(self, route: Route) -> None:
        with self._lock:
            self.calls.append(("create-route", route.target_node, route.dest_cidr))
            self.route_table[route.name] = route

    def delete_route(self, route: Route) -> None:
        with self._lock:
            self.calls.append(("delete-route", route.target_node, route.dest_cidr))
            self.route_table.pop(route.name, None)
