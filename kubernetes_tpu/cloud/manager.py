"""cloud-controller-manager (reference ``cmd/cloud-controller-manager``):
the cloud-coupled loops split out of the core controller manager so the
core control plane has zero IaaS dependencies."""

from __future__ import annotations

from typing import Callable, Optional

from ..client.clientset import Clientset
from ..controllers.manager import ControllerManager
from .controllers import CloudNodeController, RouteController, ServiceLBController
from .provider import CloudProvider

CLOUD_CONTROLLERS: dict[str, Callable] = {
    "cloud-node": CloudNodeController,
    "service-lb": ServiceLBController,
    "route": RouteController,
}


class CloudControllerManager(ControllerManager):
    """Same informer-sharing manager, cloud registry + provider wiring."""

    registry = CLOUD_CONTROLLERS

    def __init__(self, clientset: Clientset, cloud: CloudProvider,
                 enabled: Optional[list[str]] = None, clock=None, **kw):
        super().__init__(clientset, enabled=enabled, clock=clock,
                         cloud=cloud, **kw)
