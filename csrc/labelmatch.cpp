// labelmatch: interned label-selector matching engine.
//
// The host-side hot loop of the tensorizer (kubernetes_tpu/models/snapshot.py)
// is selector-vs-labelmap matching: G pod signatures x N nodes for static
// masks, and G signatures x existing-pods for spread counts — at the 5k-node
// / 150k-pod design scale that is tens of millions of string-map probes per
// batch.  The reference keeps equivalents of these loops in compiled Go
// (labels.Selector.Matches over labels.Set); this engine is the C++
// counterpart exposed through a C ABI for ctypes.
//
// Model:
//   - all strings are interned to int32 ids (one global table per engine);
//   - a labelmap is a sorted (key,value) id vector (binary-searched);
//   - a selector is a list of requirements {key, op, value-set};
//   - match_matrix evaluates |selectors| x |labelmaps| into a uint8 matrix
//     in one call (row-major), no Python in the loop.
//
// Operators mirror kubernetes_tpu/api/selectors.py exactly (including
// "missing key satisfies NotIn" and integer Gt/Lt semantics).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

enum Op : int32_t {
  OP_IN = 0,
  OP_NOT_IN = 1,
  OP_EXISTS = 2,
  OP_DOES_NOT_EXIST = 3,
  OP_GT = 4,
  OP_LT = 5,
  OP_EQ = 6,  // simple key=value (matchLabels / nodeSelector entries)
};

struct Requirement {
  int32_t key;
  int32_t op;
  std::vector<int32_t> values;       // interned value ids (IN/NOT_IN/EQ)
  long long num_value = 0;           // parsed numeric value (GT/LT)
  bool num_valid = false;
};

struct Selector {
  std::vector<Requirement> reqs;  // ANDed
};

struct LabelMap {
  // sorted by key id for binary search
  std::vector<std::pair<int32_t, int32_t>> kv;

  const int32_t* find(int32_t key) const {
    auto it = std::lower_bound(
        kv.begin(), kv.end(), key,
        [](const std::pair<int32_t, int32_t>& p, int32_t k) { return p.first < k; });
    if (it != kv.end() && it->first == key) return &it->second;
    return nullptr;
  }
};

struct Engine {
  std::unordered_map<std::string, int32_t> intern;
  std::vector<std::string> strings;
  std::vector<LabelMap> labelmaps;
  std::vector<Selector> selectors;

  int32_t intern_str(const char* s) {
    auto it = intern.find(s);
    if (it != intern.end()) return it->second;
    int32_t id = (int32_t)strings.size();
    strings.emplace_back(s);
    intern.emplace(strings.back(), id);
    return id;
  }
};

bool parse_ll(const std::string& s, long long* out) {
  if (s.empty()) return false;
  size_t i = (s[0] == '-' || s[0] == '+') ? 1 : 0;
  if (i >= s.size()) return false;
  long long v = 0;
  for (; i < s.size(); i++) {
    if (s[i] < '0' || s[i] > '9') return false;
    v = v * 10 + (s[i] - '0');
  }
  *out = (s[0] == '-') ? -v : v;
  return true;
}

bool req_matches(const Engine& e, const Requirement& r, const LabelMap& m) {
  const int32_t* val = m.find(r.key);
  switch (r.op) {
    case OP_EQ:
      return val != nullptr && !r.values.empty() && *val == r.values[0];
    case OP_IN: {
      if (val == nullptr) return false;
      for (int32_t v : r.values)
        if (v == *val) return true;
      return false;
    }
    case OP_NOT_IN: {
      if (val == nullptr) return true;  // missing key satisfies NotIn
      for (int32_t v : r.values)
        if (v == *val) return false;
      return true;
    }
    case OP_EXISTS:
      return val != nullptr;
    case OP_DOES_NOT_EXIST:
      return val == nullptr;
    case OP_GT:
    case OP_LT: {
      if (val == nullptr || !r.num_valid) return false;
      long long lhs;
      if (!parse_ll(e.strings[*val], &lhs)) return false;
      return r.op == OP_GT ? lhs > r.num_value : lhs < r.num_value;
    }
  }
  return false;
}

bool sel_matches(const Engine& e, const Selector& s, const LabelMap& m) {
  for (const auto& r : s.reqs)
    if (!req_matches(e, r, m)) return false;
  return true;
}

}  // namespace

extern "C" {

void* lm_new() { return new Engine(); }
void lm_free(void* h) { delete static_cast<Engine*>(h); }

// labelmap from parallel key/value C-string arrays; returns its id
int32_t lm_add_labelmap(void* h, const char** keys, const char** vals, int32_t n) {
  Engine* e = static_cast<Engine*>(h);
  LabelMap m;
  m.kv.reserve(n);
  for (int32_t i = 0; i < n; i++)
    m.kv.emplace_back(e->intern_str(keys[i]), e->intern_str(vals[i]));
  std::sort(m.kv.begin(), m.kv.end());
  e->labelmaps.push_back(std::move(m));
  return (int32_t)e->labelmaps.size() - 1;
}

int32_t lm_new_selector(void* h) {
  Engine* e = static_cast<Engine*>(h);
  e->selectors.emplace_back();
  return (int32_t)e->selectors.size() - 1;
}

// add one requirement to a selector
void lm_sel_add_req(void* h, int32_t sel, const char* key, int32_t op,
                    const char** values, int32_t nvalues) {
  Engine* e = static_cast<Engine*>(h);
  Requirement r;
  r.key = e->intern_str(key);
  r.op = op;
  r.values.reserve(nvalues);
  for (int32_t i = 0; i < nvalues; i++) r.values.push_back(e->intern_str(values[i]));
  if ((op == OP_GT || op == OP_LT) && nvalues == 1)
    r.num_valid = parse_ll(e->strings[r.values[0]], &r.num_value);
  e->selectors[sel].reqs.push_back(std::move(r));
}

// out[i*nl + j] = selector selector_ids[i] matches labelmap labelmap_ids[j]
void lm_match_matrix(void* h, const int32_t* selector_ids, int32_t ns,
                     const int32_t* labelmap_ids, int32_t nl, uint8_t* out) {
  Engine* e = static_cast<Engine*>(h);
  for (int32_t i = 0; i < ns; i++) {
    const Selector& s = e->selectors[selector_ids[i]];
    uint8_t* row = out + (size_t)i * nl;
    for (int32_t j = 0; j < nl; j++)
      row[j] = sel_matches(*e, s, e->labelmaps[labelmap_ids[j]]) ? 1 : 0;
  }
}

// out[j] = 1 if ANY of the selectors matches labelmap j (the spread-count
// "matches any grouping selector" probe), fused to avoid |sels| passes
void lm_match_any(void* h, const int32_t* selector_ids, int32_t ns,
                  const int32_t* labelmap_ids, int32_t nl, uint8_t* out) {
  Engine* e = static_cast<Engine*>(h);
  for (int32_t j = 0; j < nl; j++) {
    const LabelMap& m = e->labelmaps[labelmap_ids[j]];
    uint8_t hit = 0;
    for (int32_t i = 0; i < ns && !hit; i++)
      hit = sel_matches(*e, e->selectors[selector_ids[i]], m) ? 1 : 0;
    out[j] = hit;
  }
}

}  // extern "C"
