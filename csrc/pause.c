/* pause: the per-pod infrastructure process.
 *
 * Capability of the reference's pause container (build/pause/pause.c,
 * 51 lines): the one real process in every pod sandbox.  It
 *   - holds the sandbox alive (and in the reference, its netns),
 *   - reaps zombies re-parented to it as PID 1 of the pod
 *     (sigreap: waitpid WNOHANG loop on SIGCHLD),
 *   - exits cleanly on SIGINT/SIGTERM,
 *   - otherwise sleeps forever.
 *
 * Built by kubernetes_tpu.native.pause_binary(); spawned per sandbox by
 * ProcessSandboxManager when real-process sandboxes are enabled.
 */

#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

static void sigdown(int signo) {
  psignal(signo, "shutting down, got signal");
  exit(0);
}

static void sigreap(int signo) {
  (void)signo;
  while (waitpid(-1, NULL, WNOHANG) > 0)
    ;
}

int main(int argc, char **argv) {
  if (argc > 1 && strcmp(argv[1], "--version") == 0) {
    printf("ktpu-pause 1.0\n");
    return 0;
  }
  if (sigaction(SIGINT, &(struct sigaction){.sa_handler = sigdown}, NULL) < 0)
    return 1;
  if (sigaction(SIGTERM, &(struct sigaction){.sa_handler = sigdown}, NULL) < 0)
    return 2;
  if (sigaction(SIGCHLD,
                &(struct sigaction){.sa_handler = sigreap,
                                    .sa_flags = SA_NOCLDSTOP},
                NULL) < 0)
    return 3;
  for (;;)
    pause();
  return 42; /* unreachable */
}
