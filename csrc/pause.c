/* ktpu-pause: the per-pod sandbox anchor process.
 *
 * The capability (reference behavior: build/pause/pause.c — behavioral
 * spec only, implemented here with a different design): one tiny real
 * process per pod sandbox that
 *   - keeps the sandbox alive until the kubelet tears it down,
 *   - acts as the pod's PID 1, reaping any orphaned children that get
 *     re-parented onto it,
 *   - exits promptly and cleanly on SIGTERM/SIGINT.
 *
 * Design: no asynchronous signal handlers at all.  The interesting
 * signals are BLOCKED up front and consumed synchronously with
 * sigwaitinfo(2) in the main loop — child reaping and shutdown then run
 * in ordinary program context, so there is no async-signal-safety
 * surface to reason about.  (The reference era used handler-based
 * dispatch; a synchronous wait loop is the simpler modern shape.)
 *
 * Built by kubernetes_tpu.native.pause_binary(); spawned per sandbox by
 * kubelet/runtime.py ProcessSandboxManager.
 */

#define _POSIX_C_SOURCE 200809L

#include <errno.h>
#include <signal.h>
#include <stdio.h>
#include <string.h>
#include <sys/wait.h>
#include <unistd.h>

enum {
  EXIT_CLEAN = 0,
  EXIT_BAD_MASK = 10,   /* could not block the signal set */
  EXIT_WAIT_FAILED = 11 /* sigwaitinfo failed (not EINTR) */
};

static void reap_children(void) {
  /* collect every available corpse; children may exit in bursts */
  pid_t got;
  do {
    got = waitpid(-1, NULL, WNOHANG);
  } while (got > 0);
}

int main(int argc, char **argv) {
  sigset_t interesting;

  for (int i = 1; i < argc; i++) {
    if (strcmp(argv[i], "--version") == 0) {
      puts("ktpu-pause 2.0 (sigwait loop)");
      return EXIT_CLEAN;
    }
  }

  sigemptyset(&interesting);
  sigaddset(&interesting, SIGTERM);
  sigaddset(&interesting, SIGINT);
  sigaddset(&interesting, SIGCHLD);
  if (sigprocmask(SIG_BLOCK, &interesting, NULL) != 0) {
    perror("ktpu-pause: sigprocmask");
    return EXIT_BAD_MASK;
  }

  for (;;) {
    siginfo_t info;
    int signo = sigwaitinfo(&interesting, &info);
    if (signo < 0) {
      if (errno == EINTR)
        continue;
      perror("ktpu-pause: sigwaitinfo");
      return EXIT_WAIT_FAILED;
    }
    if (signo == SIGCHLD) {
      reap_children();
      continue;
    }
    /* SIGTERM / SIGINT: the kubelet (or an operator) wants us gone */
    fprintf(stderr, "ktpu-pause: exiting on %s\n", strsignal(signo));
    return EXIT_CLEAN;
  }
}
