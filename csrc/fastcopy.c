/* Deep copy for JSON-shaped Python data (dict/list/scalars), in C.
 *
 * The store deep-copies every object on every read/write/watch-emit (the
 * mutation-isolation discipline the reference enforces with its cache
 * mutation detector) — at 150k-pod scale this is the control plane's
 * single largest interpreted cost.  Python recursion pays dispatch +
 * frame overhead per node; this walks the same structure with direct
 * CPython API calls.  Scalars (str/int/float/bool/None) are immutable
 * and shared by reference, exactly like the Python implementation.
 *
 * Called via ctypes.PyDLL (GIL held).  Non-dict/list containers are
 * treated as scalars — the store's wire form never contains them.
 */

#include <Python.h>

static PyObject *fc_copy(PyObject *obj);

PyObject *fc_deepcopy(PyObject *obj) {
    return fc_copy(obj);
}

static PyObject *fc_copy(PyObject *obj) {
    if (PyDict_CheckExact(obj)) {
        PyObject *out = PyDict_New();
        if (!out) return NULL;
        PyObject *k, *v;
        Py_ssize_t pos = 0;
        while (PyDict_Next(obj, &pos, &k, &v)) {
            if (Py_EnterRecursiveCall(" in fastcopy")) { Py_DECREF(out); return NULL; }
            PyObject *cv = fc_copy(v);
            Py_LeaveRecursiveCall();
            if (!cv) { Py_DECREF(out); return NULL; }
            if (PyDict_SetItem(out, k, cv) < 0) {
                Py_DECREF(cv);
                Py_DECREF(out);
                return NULL;
            }
            Py_DECREF(cv);
        }
        return out;
    }
    if (PyList_CheckExact(obj)) {
        Py_ssize_t n = PyList_GET_SIZE(obj);
        PyObject *out = PyList_New(n);
        if (!out) return NULL;
        for (Py_ssize_t i = 0; i < n; i++) {
            if (Py_EnterRecursiveCall(" in fastcopy")) { Py_DECREF(out); return NULL; }
            PyObject *cv = fc_copy(PyList_GET_ITEM(obj, i));
            Py_LeaveRecursiveCall();
            if (!cv) { Py_DECREF(out); return NULL; }
            PyList_SET_ITEM(out, i, cv); /* steals cv */
        }
        return out;
    }
    Py_INCREF(obj);
    return obj;
}
